"""Configuration for models, training, and device meshes.

The reference keeps its entire configuration as a flat absl-flags namespace of
15 knobs (reference ``utils.py:17-33`` plus ``distributed_train.py:23``). Here
the same capability surface is three frozen dataclasses — model / training /
mesh — so configs are hashable (usable as jit static args), serializable, and
composable. The CLI layer (``transformer_tpu/cli``) still exposes the
reference's flag names for drop-in familiarity.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping

import jax.numpy as jnp

# Special-token convention, matching the reference pipeline (``utils.py:137-143``):
# pad = 0; BOS = subword_vocab_size; EOS = subword_vocab_size + 1, so a model's
# embedding table has subword_vocab_size + 2 rows (reference ``train.py:232-233``).
PAD_ID = 0


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture of one Transformer (encoder-decoder or decoder-only).

    Defaults mirror the reference flag defaults (``utils.py:18-33``):
    4 layers, d_model=512, dff=1024, 4 heads, dropout 0.1.
    """

    num_layers: int = 4
    d_model: int = 512
    num_heads: int = 4
    # Grouped-query / multi-query attention (Shazeer 2019): k/v carry this
    # many heads, each serving num_heads/num_kv_heads query heads — the
    # decode KV cache (and kv parameter count) shrinks by that factor.
    # 0 = num_heads (standard MHA, the reference's attention).
    num_kv_heads: int = 0
    dff: int = 1024
    input_vocab_size: int = 32000
    target_vocab_size: int = 32000
    dropout_rate: float = 0.1
    # Positional table sized by max positions — deliberately fixing the
    # reference's vocab-sized table (SURVEY.md §2.3.5; reference ``Encoder.py:40``).
    max_position: int = 4096
    # Post-LN matches the reference residual wiring (``Encoder.py:19-29``);
    # "pre" is offered because pre-LN is markedly more stable at depth.
    norm_scheme: str = "post"  # "post" | "pre"
    # Position encoding: "sinusoidal" = the reference's additive table
    # (``positionalencoding.py:8-23``); "rope" = rotary embeddings applied to
    # q/k in self-attention (``ops/positional.py apply_rope``) — the
    # long-context extension (relative positions, no additive table).
    position_scheme: str = "sinusoidal"  # "sinusoidal" | "rope"
    layernorm_epsilon: float = 1e-6
    # BASELINE.json configs[3]: tied src/tgt embeddings and tied output projection.
    tie_embeddings: bool = False  # share encoder/decoder embedding tables
    tie_output: bool = False  # logits = h @ embedding.T instead of a fresh Dense
    # BASELINE.json configs[4]: decoder-only causal LM (no encoder, no cross-attn).
    decoder_only: bool = False
    # Encoder-only bidirectional model (BERT family): the encoder stack with
    # padding masks only, plus the vocab head — trained with the masked-LM
    # objective (TrainConfig.objective="mlm"). No reference counterpart (the
    # reference is translation-only); completes the encoder / decoder /
    # encoder-decoder family triad.
    encoder_only: bool = False
    # Activation in the pointwise FFN; reference uses relu (``point_ffn.py:5``).
    # swiglu/geglu/reglu are the gated three-matmul variants (Shazeer 2020) —
    # the modern-LLM FFN (dense layers only; MoE experts stay ungated).
    ffn_activation: str = "relu"  # relu | gelu | silu | swiglu | geglu | reglu
    # Compute dtype: bf16 keeps the MXU fed at full rate; params stay fp32.
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # Attention implementation: "xla" (einsum softmax einsum, XLA-fused),
    # "flash" (Pallas blockwise kernel), "ring" (sequence-parallel ring over
    # ICI), "ulysses" (sequence-parallel head all-to-all). ring/ulysses train
    # through DistributedTrainer with MeshConfig(seq>1).
    attention_impl: str = "xla"
    # Block sizes for the Pallas flash-attention kernel.
    flash_block_q: int = 128
    flash_block_k: int = 128
    # Rematerialize each layer's activations in the backward pass
    # (jax.checkpoint): trades ~1/3 more FLOPs for O(layers) less activation
    # HBM — the standard lever for long-context configs (BASELINE configs[4]).
    remat: bool = False
    # What remat may KEEP from the forward pass ("full" = keep nothing,
    # recompute everything — minimum memory, ~1/3 extra FLOPs; "dots" =
    # jax.checkpoint_policies.dots_with_no_batch_dims_saveable: save matmul
    # outputs, recompute only the cheap elementwise/bandwidth-bound ops —
    # most of the memory win at a fraction of the recompute, usually the
    # better point on TPUs where MXU FLOPs are the scarce resource).
    remat_policy: str = "full"  # "full" | "dots"
    # Sliding-window (local) attention for CAUSAL self-attention: each
    # position attends only the last `attention_window` positions
    # (Mistral-style). Applies to decoder self-attention and decoder-only
    # LMs; encoder self-attention and cross-attention are unaffected.
    # Structural in the flash kernel (out-of-band tiles skipped: per-row
    # compute O(window), not O(S)); banded mask under xla; rolling O(window)
    # KV cache at decode; under ring sequence parallelism out-of-band hops
    # stop the ring early (ICI traffic O(window)); ulysses applies the band
    # in its per-device flash call. 0 = full attention.
    attention_window: int = 0
    # int8 decode KV cache (ops/attention.py init_cache(quantize=True)):
    # k/v stored int8 with one fp32 scale per (position, head) row,
    # dequantized on read — ~2x (vs bf16) to ~4x (vs fp32) less HBM for the
    # long-context serving bottleneck. Decode-only; training is unaffected.
    kv_cache_int8: bool = False
    # Mixture-of-Experts FFN (capability extension; the reference's FFN is
    # dense, ``point_ffn.py:3-7``). 0 = dense FFN everywhere. When > 0, every
    # ``moe_every``-th layer replaces its FFN with a ``moe_experts``-expert
    # MoE (``ops/moe.py``), sharded over the mesh's ``expert`` axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_every: int = 1  # 1 = every layer; 2 = every other layer (GShard style)
    moe_aux_weight: float = 0.01  # load-balance loss weight in the objective

    def __post_init__(self) -> None:
        if self.d_model % self.num_heads != 0:
            # Same invariant the reference asserts (``Attention.py:42``).
            raise ValueError(
                f"d_model ({self.d_model}) must be divisible by num_heads "
                f"({self.num_heads})"
            )
        if self.encoder_only and self.decoder_only:
            raise ValueError(
                "encoder_only and decoder_only are mutually exclusive"
            )
        if self.encoder_only and self.input_vocab_size != self.target_vocab_size:
            # One tower, one id space: the MLM [MASK] id is
            # input_vocab_size - 1 while the head/loss are sized by
            # target_vocab_size — a mismatch would silently clamp labels.
            raise ValueError(
                "encoder_only models use one id space: input_vocab_size "
                f"({self.input_vocab_size}) must equal target_vocab_size "
                f"({self.target_vocab_size})"
            )
        if self.norm_scheme not in ("post", "pre"):
            raise ValueError(f"norm_scheme must be 'post' or 'pre', got {self.norm_scheme!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got {self.remat_policy!r}"
            )
        if self.attention_window < 0:
            raise ValueError(
                f"attention_window must be >= 0, got {self.attention_window}"
            )
        if self.position_scheme not in ("sinusoidal", "rope"):
            raise ValueError(
                f"position_scheme must be 'sinusoidal' or 'rope', got "
                f"{self.position_scheme!r}"
            )
        if self.position_scheme == "rope" and (self.d_model // self.num_heads) % 2:
            raise ValueError(
                "position_scheme='rope' needs an even head_dim "
                f"(got {self.d_model // self.num_heads})"
            )
        # Single source of truth for activation names: the op registry.
        from transformer_tpu.ops.ffn import FFN_ACTIVATIONS, is_gated

        if self.ffn_activation not in FFN_ACTIVATIONS:
            raise ValueError(f"unknown ffn_activation {self.ffn_activation!r}")
        if self.moe_experts and is_gated(self.ffn_activation):
            raise ValueError(
                "MoE experts use the ungated FFN: pick an ungated activation "
                f"with moe_experts > 0 (got {self.ffn_activation!r})"
            )
        if self.attention_impl not in ("xla", "flash", "ring", "ulysses"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.moe_experts < 0 or self.moe_top_k < 1 or self.moe_every < 1:
            raise ValueError(
                "moe_experts must be >= 0, moe_top_k and moe_every >= 1 "
                f"(got {self.moe_experts}/{self.moe_top_k}/{self.moe_every})"
            )
        if self.moe_experts and self.moe_top_k > self.moe_experts:
            raise ValueError(
                f"moe_top_k ({self.moe_top_k}) cannot exceed moe_experts "
                f"({self.moe_experts})"
            )
        if self.num_kv_heads < 0 or self.num_kv_heads > self.num_heads or (
            self.num_kv_heads and self.num_heads % self.num_kv_heads
        ):
            raise ValueError(
                f"num_kv_heads ({self.num_kv_heads}) must be 0 (= num_heads) "
                f"or a positive divisor of num_heads ({self.num_heads})"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def params_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.param_dtype)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training-engine knobs; defaults mirror the reference (``utils.py:18-33``,
    ``train.py:21-22,65-66``)."""

    batch_size: int = 64
    sequence_length: int = 50
    epochs: int = 4
    # Noam schedule warmup. The reference defaults to 60000 (``train.py:22``),
    # not the paper's 4000 — kept as the default for parity.
    warmup_steps: int = 60000
    # LR schedule family (train/schedule.py): "noam" is the reference's
    # CustomSchedule; "cosine"/"constant" warm up linearly to ``peak_lr``
    # (required > 0 for those), cosine decaying to peak_lr/10 at
    # ``lr_decay_steps`` (required for cosine).
    lr_schedule: str = "noam"  # "noam" | "cosine" | "constant"
    peak_lr: float = 0.0
    lr_decay_steps: int = 0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.98
    adam_epsilon: float = 1e-9
    # "adam": the reference's optimizer exactly (``train.py:65-66``).
    # "adafactor": factored second moments — O(d_in + d_out) optimizer state
    # per matrix instead of Adam's 2x params, the standard memory lever for
    # big-model training.
    # "adamw": decoupled weight decay (``weight_decay``) on matrices only
    # (vectors — biases, layernorms — are exempt).
    optimizer: str = "adam"  # "adam" | "adafactor" | "adamw"
    weight_decay: float = 0.0  # adamw only
    label_smoothing: float = 0.0  # BASELINE.json configs[2] uses > 0
    # "tokens": mean CE over non-pad tokens (the sane default).
    # "batch": sum of per-token CE divided by global batch size — the
    # reference's exact normalization (``train.py:83-88``), offered for parity.
    loss_normalization: str = "tokens"
    max_grad_norm: float = 0.0  # 0 disables clipping (reference has none)
    buffer_size: int = 100000  # shuffle buffer (reference ``utils.py:19``)
    eval_every_steps: int = 500
    # In-loop eval batch cap: the reference either runs the FULL test set
    # every 100 steps (``train.py:193-195``) or ~1 batch (``distributed_
    # train.py:94``) — both defects (SURVEY §2.3.3/.6). Bounded and
    # configurable here; 0 = no cap (full test set).
    eval_max_batches: int = 8
    # Early stopping: stop after this many consecutive epochs without
    # end-of-epoch eval-loss improvement (0 = off; needs a test dataset).
    # The reference always runs all epochs (``train.py:180``).
    early_stop_patience: int = 0
    log_every_steps: int = 100
    checkpoint_every_epochs: int = 5  # intent of the reference's (buggy) save cond
    max_ckpt_keep: int = 5
    ckpt_path: str = "model_dist"
    enable_function: bool = True  # jit on/off — the reference's eager-debug flag
    seed: int = 0
    # GPipe microbatches per step when the mesh has a pipe axis; 0 = one
    # microbatch per stage (parallel/pipeline.py).
    pp_microbatches: int = 0
    # Pipeline schedule: "gpipe" (forward schedule + autodiff backward,
    # activation stash grows with pp_microbatches) or "1f1b" (manual
    # interleaved forward/backward schedule, stash bounded at 2*stages-1
    # microbatches regardless of pp_microbatches — the pod-scale memory
    # profile). 1f1b supports dense models (decoder-only and seq2seq —
    # the seq2seq decoder stack runs the engine, the encoder half GPipe)
    # on data x fsdp x model x pipe meshes (parallel/pipeline.py
    # pipeline_train_1f1b).
    pp_schedule: str = "gpipe"
    # Gradient accumulation: split each batch into this many sequential
    # micro-steps and sum gradients before one optimizer update — train
    # big-model global batches on small-HBM chips. 1 = off.
    grad_accum_steps: int = 1
    # Chunked loss: compute the final vocab projection + CE over this many
    # sequence slices (train/loss.py chunked_cross_entropy_from_hidden) so
    # the full (B, S, V) logits tensor is never materialized — the memory
    # lever for big-vocab/long-context configs. 1 = off.
    loss_chunks: int = 1
    # Host-dispatch amortization: run this many optimizer steps inside ONE
    # jitted lax.scan per host→device dispatch (trainer.py
    # make_multistep_train_step). At small step times the per-step Python/
    # runtime dispatch is a measurable share of wall clock (BASELINE.md
    # [deviceloop] probe); K steps per dispatch divide it by K. Orthogonal
    # to grad_accum_steps (each inner step is still a full optimizer
    # update). Trade-off: preemption/log/eval granularity becomes K steps.
    # 1 = off.
    steps_per_dispatch: int = 1
    # Training objective: "causal" (teacher-forcing shift — seq2seq and
    # decoder-only LM) or "mlm" (BERT-style dynamic masked-LM for
    # ModelConfig.encoder_only: 15% of non-pad positions selected per step,
    # 80% [MASK] / 10% random / 10% kept; loss only on selected positions).
    # The [MASK] id is the model's top input id (input_vocab_size - 1) —
    # size the vocab one larger than the tokenizer's (train/mlm.py).
    objective: str = "causal"
    mlm_mask_rate: float = 0.15
    # Special ids excluded from MLM selection AND from the 10% random-
    # replacement draw (BERT/RoBERTa exclude specials from both). None =
    # auto: the framework's vocab layout puts BOS/EOS at the two ids
    # directly below [MASK] (tokenizer bos=vocab_size, eos=vocab_size+1,
    # mask=model_vocab+1-1 — see cli/flags.py MLM sizing), so auto excludes
    # (mask_id-2, mask_id-1). Pass () to exclude nothing (custom layouts).
    mlm_excluded_ids: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if self.loss_normalization not in ("tokens", "batch"):
            raise ValueError(
                f"loss_normalization must be 'tokens' or 'batch', got {self.loss_normalization!r}"
            )
        if self.objective not in ("causal", "mlm"):
            raise ValueError(
                f"objective must be 'causal' or 'mlm', got {self.objective!r}"
            )
        if not 0.0 < self.mlm_mask_rate < 1.0:
            raise ValueError(
                f"mlm_mask_rate must be in (0, 1), got {self.mlm_mask_rate}"
            )
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"pp_schedule must be 'gpipe' or '1f1b', got {self.pp_schedule!r}"
            )
        if self.optimizer not in ("adam", "adafactor", "adamw"):
            raise ValueError(
                "optimizer must be 'adam', 'adafactor' or 'adamw', got "
                f"{self.optimizer!r}"
            )
        if self.weight_decay and self.optimizer != "adamw":
            raise ValueError(
                "weight_decay > 0 requires optimizer='adamw' (adam/adafactor "
                "would silently ignore it)"
            )
        if self.lr_schedule not in ("noam", "cosine", "constant"):
            raise ValueError(
                f"lr_schedule must be noam/cosine/constant, got {self.lr_schedule!r}"
            )
        if self.lr_schedule != "noam" and self.peak_lr <= 0:
            raise ValueError(
                f"lr_schedule={self.lr_schedule!r} needs peak_lr > 0"
            )
        if self.lr_schedule == "cosine" and self.lr_decay_steps <= self.warmup_steps:
            raise ValueError(
                "lr_schedule='cosine' needs lr_decay_steps > warmup_steps "
                f"(got {self.lr_decay_steps} <= {self.warmup_steps})"
            )
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {self.steps_per_dispatch}"
            )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. Axis names are the framework-wide vocabulary used
    by every PartitionSpec:

    - ``data``: data parallelism (gradient psum over ICI — the TPU-native
      replacement for the reference's NCCL all-reduce, ``distributed_train.py:58-62``)
    - ``fsdp``: parameter/optimizer sharding (zero-style), rides the data axis
    - ``model``: tensor parallelism (attention heads / dff)
    - ``seq``: sequence/context parallelism (ring attention over ICI)
    - ``pipe``: pipeline parallelism (GPipe microbatch schedule, activations
      ppermute between stages — ``parallel/pipeline.py``). Memory note: the
      pipe axis partitions *compute*; combine with ``fsdp`` to also shard
      stage parameters/optimizer state, otherwise each device holds a full
      replica of the stacked layer params.
    - ``expert``: expert parallelism (MoE expert weights sharded over ICI,
      token slots all-to-all'd to their experts by GSPMD — ``ops/moe.py``).
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    # Multi-slice: how many DCN-connected slices (or processes, off-TPU) the
    # DATA axis spans. Must divide ``data``. The mesh is then built hybrid
    # (jax mesh_utils): the slow inter-slice DCN hops carry only the
    # data-parallel gradient all-reduce, while fsdp/model/seq/pipe/expert
    # collectives stay on intra-slice ICI — the "collectives ride ICI, not
    # DCN" layout. 1 = single slice (plain mesh).
    dcn_data: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.fsdp * self.model * self.seq * self.pipe * self.expert

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("data", "fsdp", "model", "seq", "pipe", "expert")

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.model, self.seq, self.pipe, self.expert)


def config_to_json(cfg: Any) -> str:
    """Serialize any of the config dataclasses to JSON (for export/checkpoints)."""
    return json.dumps(dataclasses.asdict(cfg), indent=2, sort_keys=True)


def config_from_json(cls: type, payload: str | Mapping[str, Any]):
    data = json.loads(payload) if isinstance(payload, str) else dict(payload)
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in data.items() if k in known})
