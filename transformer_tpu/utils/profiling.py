"""Tracing / profiling subsystem.

The reference's only observability is wall-clock prints (``train.py:186,213``,
``distributed_train.py:76,81,99,121``) plus loss/accuracy scalars; its
de-facto "debug mode" is ``--enable_function=False`` (``utils.py:30``,
``train.py:175-177``), which this framework preserves as the un-jitted eager
path. This module is the TPU-native upgrade:

- :class:`Profiler` captures an XLA device trace for a step window
  ``[start_step, start_step + num_steps)`` via ``jax.profiler`` and writes a
  TensorBoard-profile-compatible dump.
- :func:`annotate` labels host-side regions so they show up on the trace
  timeline.
- :class:`StepTimer` keeps an online step-duration distribution and
  throughput estimate — the structured replacement for the reference's
  printed per-step deltas.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

from transformer_tpu.obs.quantiles import StreamingHistogram


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Persist compiled executables across processes.

    The measurement/watchdog pattern in this repo runs one subprocess per
    TPU measurement (a poisoned backend must not outlive its process), so
    every pass re-pays the full XLA compile — ~210 s for the base model
    through the tunneled backend, a third of an 8-epoch resumable BLEU
    pass. A persistent on-disk cache turns every compile after the first
    into a disk load. Backends whose PJRT plugin cannot serialize
    executables simply miss the cache (JAX warns and compiles as before),
    so enabling this is always safe.

    ``cache_dir`` defaults to ``$TRANSFORMER_TPU_JAX_CACHE`` or a /tmp
    path shared by all of this repo's processes; setting the env var to
    ``off`` (or ``0``) disables caching entirely. Returns the directory
    ('' when disabled).
    """
    cache_dir = cache_dir or os.environ.get(
        "TRANSFORMER_TPU_JAX_CACHE",
        # uid-scoped: on a shared host a world-shared /tmp path could be
        # pre-created by (and readable/writable to) another user — both a
        # silent cache-miss-forever and an arbitrary-executable hazard.
        f"/tmp/transformer_tpu_jax_cache_{os.getuid()}",
    )
    if cache_dir in ("off", "0"):
        return ""
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Small compiles are cheaper to redo than to hash + load.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # jax binds the cache directory ONCE, lazily, at the first jit after
    # import — a dir configured after any compile has happened is silently
    # ignored for the life of the process. Reset so this call's dir takes
    # effect no matter when it runs (the CLI enables the cache after flag
    # parsing, by which point absl/jax warmup may already have compiled).
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass  # older jax: the lazy init below is the only binding anyway
    return cache_dir


class Profiler:
    """Capture one jax.profiler trace over a window of training steps.

    Drive it from a training loop with ``maybe_trace(step)`` once per step;
    the trace starts when ``step == start_step`` and stops ``num_steps``
    later (or at ``close()``, whichever comes first).
    """

    def __init__(self, log_dir: str, start_step: int = 2, num_steps: int = 3):
        self.log_dir = log_dir
        # Relative to the first observed step, so a run restored at step N
        # still skips `start_step` warmup (compile) steps before tracing.
        self.start_step = start_step
        self.num_steps = num_steps
        self._first_step: int | None = None
        self._active = False
        self._done = False

    def maybe_trace(self, step: int, block_on=None) -> None:
        if self._done:
            return
        if self._first_step is None:
            self._first_step = step
        rel = step - self._first_step
        if not self._active and rel >= self.start_step:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._stop_at = step + self.num_steps
        elif self._active and step >= self._stop_at:
            self.stop(block_on)

    def stop(self, block_on=None) -> None:
        """End the capture. Pass the training state (or any output of the
        profiled steps) as ``block_on`` so enqueued device work finishes
        inside the trace — without it, async-dispatched steps may still be
        running when the capture closes."""
        if self._active:
            if block_on is not None:
                jax.block_until_ready(block_on)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    close = stop


@contextlib.contextmanager
def annotate(name: str):
    """Label a host-side region on the profiler timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Step throughput from wall clock between *sync points*.

    Under async dispatch a jitted step returns as soon as it is enqueued, so
    per-call deltas measure host dispatch, not device time. This timer only
    trusts windows closed by :meth:`sync`, which the caller invokes right
    after a genuinely blocking read (a metric ``device_get``, an epoch
    boundary): ``tick()`` counts steps; ``sync()`` closes the window and
    attributes its wall time to the steps inside it.
    """

    def __init__(self, tokens_per_step: int = 0):
        self.tokens_per_step = tokens_per_step
        # Online step-duration distribution (p50/p95/p99), fed one window at
        # a time by sync(). The histogram instance is the obs-registry reuse
        # point: Trainer binds it into a registry Histogram
        # (`registry.histogram(name, hist=timer.histogram)`), so telemetry
        # exports the SAME sample stream with no duplicate quantile code.
        # Survives reset(): reset() reopens the throughput window per epoch,
        # but the duration distribution is a run-level statistic.
        self.histogram = StreamingHistogram()
        self.reset()

    def reset(self) -> None:
        self._window_steps = 0
        self._window_tokens = 0
        self._window_start: float | None = None
        self._total_steps = 0
        self._total_tokens = 0
        self._total_time = 0.0

    def tick(self, tokens: int | None = None, steps: int = 1) -> None:
        """Call once per dispatch. ``tokens`` overrides the fixed
        ``tokens_per_step`` for that dispatch — length-bucketed batches
        process fewer tokens than the nominal batch×sequence_length.
        ``steps`` > 1 when one dispatch covers several optimizer steps
        (TrainConfig.steps_per_dispatch); ``tokens`` then counts the whole
        group."""
        if self._window_start is None:
            self._window_start = time.perf_counter()
        self._window_steps += steps
        self._window_tokens += (
            self.tokens_per_step * steps if tokens is None else tokens
        )

    def sync(self) -> None:
        """Close the current window — call immediately after a blocking read
        of step outputs, so the elapsed time covers completed device work."""
        if self._window_start is None or self._window_steps == 0:
            return
        window = time.perf_counter() - self._window_start
        self._total_time += window
        self._total_steps += self._window_steps
        self._total_tokens += self._window_tokens
        # Per-step duration is only observable at window granularity under
        # async dispatch: attribute the window's wall time evenly to the
        # steps inside it (n identical samples keeps step-count weighting).
        self.histogram.observe(window / self._window_steps, n=self._window_steps)
        self._window_steps = 0
        self._window_tokens = 0
        self._window_start = None

    @property
    def count(self) -> int:
        return self._total_steps

    @property
    def total_tokens(self) -> int:
        return self._total_tokens

    @property
    def total_time_s(self) -> float:
        return self._total_time

    @property
    def mean_s(self) -> float:
        return self._total_time / self._total_steps if self._total_steps else 0.0

    @property
    def steps_per_sec(self) -> float:
        return self._total_steps / self._total_time if self._total_time > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self._total_tokens / self._total_time if self._total_time > 0 else 0.0

    def summary(self) -> str:
        if not self._total_steps:
            return "no steps timed"
        msg = (
            f"{self.count} steps: mean {self.mean_s * 1e3:.1f}ms "
            f"({self.steps_per_sec:.2f} steps/s"
        )
        if self._total_tokens:
            msg += f", {self.tokens_per_sec:,.0f} tokens/s"
        msg += ")"
        if self.histogram.count:
            p = self.histogram.percentiles()
            msg += (
                f" p50 {p['p50'] * 1e3:.1f}ms p95 {p['p95'] * 1e3:.1f}ms "
                f"p99 {p['p99'] * 1e3:.1f}ms"
            )
        return msg
