"""Utilities: TensorBoard event writing, BLEU, profiling/tracing,
preemption handling, determinism audits."""

from transformer_tpu.utils.bleu import corpus_bleu
from transformer_tpu.utils.preemption import PreemptionGuard, tree_checksum
from transformer_tpu.utils.profiling import (
    Profiler,
    StepTimer,
    annotate,
    enable_compilation_cache,
)
from transformer_tpu.utils.tensorboard import SummaryWriter

__all__ = [
    "PreemptionGuard",
    "Profiler",
    "StepTimer",
    "SummaryWriter",
    "annotate",
    "corpus_bleu",
    "enable_compilation_cache",
    "tree_checksum",
]
