"""Utilities: TensorBoard event writing, BLEU, profiling helpers."""

from transformer_tpu.utils.bleu import corpus_bleu
from transformer_tpu.utils.tensorboard import SummaryWriter

__all__ = ["SummaryWriter", "corpus_bleu"]
