"""Failure detection and graceful-preemption handling.

The reference has no recovery story beyond manual restart + restore-latest
(``train.py:159-164``) — and it only restores *after* training
(``train.py:242-243``, SURVEY §5). This framework restores at start
(``Trainer.fit``) and adds the piece TPU fleets actually need: maintenance
events and spot reclaims deliver SIGTERM with a grace window, so a training
run must checkpoint *on signal* rather than lose the epoch.

Also here: :func:`tree_checksum`, a deterministic pytree fingerprint used as
the framework's determinism/race audit (SURVEY §5 — the reference has no
concurrency of its own to race; in SPMD the equivalent failure mode is
replicas drifting apart, e.g. non-deterministic collectives or host-side
data skew, which fingerprint comparison across runs/hosts catches).
"""

from __future__ import annotations

import os
import signal
import zlib
from typing import Any

import jax
import numpy as np


class PreemptionGuard:
    """Latches termination signals so the training loop can exit cleanly.

    Use as a context manager around the loop; check ``should_stop`` between
    steps. Handlers are chained — a previously-installed handler still runs —
    and restored on exit.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._previous: dict[int, Any] = {}
        self.should_stop = False
        self.signal_received: int | None = None

    def _handler(self, signum, frame):
        if self.should_stop:
            # Second signal: the user/platform insists — defer to the previous
            # handler (for SIGINT that's KeyboardInterrupt) for a hard stop.
            prev = self._previous.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                # SIG_DFL/SIG_IGN are ints, not callables: restore the
                # original disposition and re-deliver the signal so the
                # default action (e.g. terminate, for SIGTERM) actually runs.
                signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        self.should_stop = True
        self.signal_received = signum
        # First signal only latches; chaining Python's default SIGINT handler
        # here would raise KeyboardInterrupt and defeat the graceful path.
        prev = self._previous.get(signum)
        if callable(prev) and prev is not signal.default_int_handler:
            prev(signum, frame)

    def __enter__(self) -> "PreemptionGuard":
        for s in self._signals:
            self._previous[s] = signal.getsignal(s)
            signal.signal(s, self._handler)
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()


def tree_checksum(tree: Any) -> int:
    """Deterministic fingerprint of a pytree of arrays (params, optimizer
    state). Equal trees ⇒ equal checksums, across processes and runs — the
    cross-replica/run determinism audit."""
    crc = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        crc = zlib.crc32(str(path).encode(), crc)
        crc = zlib.crc32(str(arr.dtype).encode(), crc)
        crc = zlib.crc32(str(arr.shape).encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc
