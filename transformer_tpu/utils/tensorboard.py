"""Minimal TensorBoard event-file writer (no TensorFlow dependency).

The reference logs loss/accuracy scalars per epoch through
``tf.summary.create_file_writer`` (``train.py:75-76,200-206``). TensorFlow is
not part of this stack, so this module writes the ``tfevents`` wire format
directly: TFRecord framing (length + masked-crc32c) around hand-encoded
``Event``/``Summary`` protobuf messages. Two record kinds cover everything
this repo logs: scalar summaries (three proto fields) and histogram
summaries (``HistogramProto`` — the obs sink exports step-time / latency
distributions from ``obs.quantiles.StreamingHistogram`` bucket state).

Files are readable by stock TensorBoard: ``events.out.tfevents.<ts>.<host>``.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ----------------------------------------------------------------- crc32c
_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf enc
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _encode_scalar_event(tag_name: str, value: float, step: int, wall_time: float) -> bytes:
    name = tag_name.encode("utf-8")
    summary_value = (
        _tag(1, 2) + _varint(len(name)) + name  # Value.tag
        + _tag(2, 5) + struct.pack("<f", value)  # Value.simple_value
    )
    summary = _tag(1, 2) + _varint(len(summary_value)) + summary_value  # Summary.value
    return (
        _tag(1, 1) + struct.pack("<d", wall_time)  # Event.wall_time
        + _tag(2, 0) + _varint(step)  # Event.step
        + _tag(5, 2) + _varint(len(summary)) + summary  # Event.summary
    )


def _encode_histogram_event(
    tag_name: str,
    step: int,
    wall_time: float,
    *,
    hist_min: float,
    hist_max: float,
    num: float,
    total: float,
    sum_squares: float,
    bucket_limits: list[float],
    bucket_counts: list[float],
) -> bytes:
    """Event carrying one ``Summary.Value.histo`` (HistogramProto: min=1,
    max=2, num=3, sum=4, sum_squares=5, bucket_limit=6 packed, bucket=7
    packed — the shape stock TensorBoard's histogram dashboard reads)."""
    histo = (
        _tag(1, 1) + struct.pack("<d", hist_min)
        + _tag(2, 1) + struct.pack("<d", hist_max)
        + _tag(3, 1) + struct.pack("<d", num)
        + _tag(4, 1) + struct.pack("<d", total)
        + _tag(5, 1) + struct.pack("<d", sum_squares)
    )
    if bucket_limits:
        packed = b"".join(struct.pack("<d", v) for v in bucket_limits)
        histo += _tag(6, 2) + _varint(len(packed)) + packed
        packed = b"".join(struct.pack("<d", v) for v in bucket_counts)
        histo += _tag(7, 2) + _varint(len(packed)) + packed
    name = tag_name.encode("utf-8")
    summary_value = (
        _tag(1, 2) + _varint(len(name)) + name  # Value.tag
        # Value.histo is field 5 in summary.proto (4 is Image — a histogram
        # encoded there renders as nothing in the histogram dashboard).
        + _tag(5, 2) + _varint(len(histo)) + histo
    )
    summary = _tag(1, 2) + _varint(len(summary_value)) + summary_value
    return (
        _tag(1, 1) + struct.pack("<d", wall_time)  # Event.wall_time
        + _tag(2, 0) + _varint(step)  # Event.step
        + _tag(5, 2) + _varint(len(summary)) + summary  # Event.summary
    )


def _encode_file_version(wall_time: float) -> bytes:
    version = b"brain.Event:2"
    return (
        _tag(1, 1) + struct.pack("<d", wall_time)
        + _tag(3, 2) + _varint(len(version)) + version  # Event.file_version
    )


class SummaryWriter:
    """Append-only scalar summary writer producing stock-TensorBoard-readable
    event files."""

    def __init__(self, log_dir: str) -> None:
        os.makedirs(log_dir, exist_ok=True)
        ts = time.time()
        fname = f"events.out.tfevents.{int(ts)}.{socket.gethostname()}"
        self._path = os.path.join(log_dir, fname)
        self._file = open(self._path, "ab")
        self._write_record(_encode_file_version(ts))

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(payload)
        self._file.write(struct.pack("<I", _masked_crc(payload)))

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(
            _encode_scalar_event(tag, float(value), int(step), time.time())
        )

    def histogram(self, tag: str, hist, step: int) -> None:
        """Write one histogram summary from any object with the
        ``obs.quantiles.StreamingHistogram`` export surface (``count``,
        ``total``, ``sum_squares``, ``min``, ``max``, ``buckets()``).
        Duck-typed so this module stays import-free of the obs package.
        Empty distributions are skipped (TensorBoard rejects num=0)."""
        if not hist.count:
            return
        limits = [float(b) for b, _ in hist.buckets()]
        counts = [float(c) for _, c in hist.buckets()]
        self._write_record(
            _encode_histogram_event(
                tag, int(step), time.time(),
                hist_min=float(hist.min), hist_max=float(hist.max),
                num=float(hist.count), total=float(hist.total),
                sum_squares=float(hist.sum_squares),
                bucket_limits=limits, bucket_counts=counts,
            )
        )

    def flush(self) -> None:
        self._file.flush()

    def close(self) -> None:
        self._file.close()

    @property
    def path(self) -> str:
        return self._path
