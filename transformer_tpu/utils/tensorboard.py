"""Minimal TensorBoard event-file writer (no TensorFlow dependency).

The reference logs loss/accuracy scalars per epoch through
``tf.summary.create_file_writer`` (``train.py:75-76,200-206``). TensorFlow is
not part of this stack, so this module writes the ``tfevents`` wire format
directly: TFRecord framing (length + masked-crc32c) around hand-encoded
``Event``/``Summary`` protobuf messages. Only scalar summaries are needed —
the full proto surface is three fields.

Files are readable by stock TensorBoard: ``events.out.tfevents.<ts>.<host>``.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ----------------------------------------------------------------- crc32c
_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf enc
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _encode_scalar_event(tag_name: str, value: float, step: int, wall_time: float) -> bytes:
    name = tag_name.encode("utf-8")
    summary_value = (
        _tag(1, 2) + _varint(len(name)) + name  # Value.tag
        + _tag(2, 5) + struct.pack("<f", value)  # Value.simple_value
    )
    summary = _tag(1, 2) + _varint(len(summary_value)) + summary_value  # Summary.value
    return (
        _tag(1, 1) + struct.pack("<d", wall_time)  # Event.wall_time
        + _tag(2, 0) + _varint(step)  # Event.step
        + _tag(5, 2) + _varint(len(summary)) + summary  # Event.summary
    )


def _encode_file_version(wall_time: float) -> bytes:
    version = b"brain.Event:2"
    return (
        _tag(1, 1) + struct.pack("<d", wall_time)
        + _tag(3, 2) + _varint(len(version)) + version  # Event.file_version
    )


class SummaryWriter:
    """Append-only scalar summary writer producing stock-TensorBoard-readable
    event files."""

    def __init__(self, log_dir: str) -> None:
        os.makedirs(log_dir, exist_ok=True)
        ts = time.time()
        fname = f"events.out.tfevents.{int(ts)}.{socket.gethostname()}"
        self._path = os.path.join(log_dir, fname)
        self._file = open(self._path, "ab")
        self._write_record(_encode_file_version(ts))

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(payload)
        self._file.write(struct.pack("<I", _masked_crc(payload)))

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(
            _encode_scalar_event(tag, float(value), int(step), time.time())
        )

    def flush(self) -> None:
        self._file.flush()

    def close(self) -> None:
        self._file.close()

    @property
    def path(self) -> str:
        return self._path
