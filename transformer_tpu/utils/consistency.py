"""Cross-replica / determinism sanitizers.

The reference has no concurrency checks at all (SURVEY §5 "race detection /
sanitizers: absent entirely" — its only concurrency surface is the mirrored
strategy, ``distributed_train.py:58-62``). On a TPU pod the equivalent risks
are real and silent: per-process RNG or data-order divergence leaves each
host training a slightly different model (replicated arrays stop being
replicas), and a nondeterministic collective or seed bug makes runs
unreproducible. These helpers make both failure modes assertable:

- :func:`tree_fingerprint` — bit-exact per-leaf digest of a pytree.
- :func:`assert_cross_process_consistent` — every process must hold
  bit-identical bytes for (logically replicated) arrays.
- :func:`assert_step_deterministic` — the same jitted step on the same
  inputs must produce bit-identical outputs.

All comparisons are over raw bytes (crc32), never float equality: NaN-laden
but identical state compares equal (a loss blowup must read as a numerics
problem, not a fake replication bug), and no two genuinely different byte
patterns compare equal through a lossy stats summary.
"""

from __future__ import annotations

import zlib
from typing import Any

import jax
import numpy as np

from transformer_tpu.train.checkpoint import _SEP, _path_elem


def _leaf_items(tree: Any):
    """(flat key, ORIGINAL leaf) pairs — same key scheme as the checkpoint
    format, leaves untouched (no device_get) so callers can inspect
    shardings before deciding to fetch."""
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    for p, leaf in leaves_with_path:
        yield _SEP.join(_path_elem(e) for e in p), leaf


def _leaf_crc(leaf: Any) -> int:
    """Bit-exact digest of one leaf: crc32 over dtype, shape, and raw bytes
    (host leaves as-is; device arrays fetched)."""
    a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
    h = zlib.crc32(f"{a.dtype}:{a.shape}:".encode())
    return zlib.crc32(a.tobytes(), h) & 0xFFFFFFFF


def _is_comparable(leaf: Any) -> bool:
    """Only fully-replicated device arrays (and plain host arrays) are
    required to be byte-identical across processes — sharded leaves (FSDP/
    TP/EP) legitimately hold different index ranges per process and are
    kept consistent by GSPMD itself. Checked on the ORIGINAL leaf, before
    any device_get: fetching a multi-host-sharded array would raise (spans
    non-addressable devices), not skip."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return True  # host-side numpy: every process derived it identically
    return bool(sharding.is_fully_replicated)


def tree_fingerprint(tree: Any) -> dict[str, int]:
    """Bit-exact digest of a pytree: one crc32 per leaf, keyed by the same
    flat path names the checkpoint format uses, so mismatches name the
    offending parameter."""
    return {key: _leaf_crc(leaf) for key, leaf in _leaf_items(tree)}


def fingerprints_equal(a: dict[str, int], b: dict[str, int]) -> list[str]:
    """Names of leaves whose digests differ."""
    bad = [k for k in a if a[k] != b.get(k)]
    bad += [k for k in b if k not in a]
    return sorted(set(bad))


def assert_cross_process_consistent(tree: Any, label: str = "params") -> None:
    """Every process must hold bit-identical bytes for the REPLICATED
    leaves of ``tree`` (see :func:`_is_comparable`).

    Catches silent replica divergence (per-host RNG/data-order bugs).
    Single-process: trivially passes, without fetching anything. Multi-
    process: one crc per kept leaf is allgathered over the DCN and compared
    on every host; raises ``RuntimeError`` naming the first diverged
    leaves.
    """
    if jax.process_count() == 1:
        return
    keys, crcs = [], []
    for key, leaf in _leaf_items(tree):
        if not _is_comparable(leaf):
            continue
        keys.append(key)
        crcs.append(_leaf_crc(leaf))
    if not keys:
        return  # everything sharded (pure FSDP/TP): nothing replicated to compare
    from jax.experimental import multihost_utils

    local = np.asarray(crcs, dtype=np.uint32)
    gathered = np.asarray(multihost_utils.process_allgather(local))  # (P, L)
    mismatch = (gathered != gathered[0:1]).any(axis=0)
    if mismatch.any():
        bad = [keys[i] for i in np.flatnonzero(mismatch)]
        raise RuntimeError(
            f"cross-process divergence in {label}: {len(bad)} leaves differ "
            f"across the {gathered.shape[0]} processes, starting with "
            f"{bad[:5]} — replicated state is no longer replicated "
            "(per-host RNG or data-order bug)"
        )


def assert_step_deterministic(
    step_fn, *args, label: str = "train step"
) -> None:
    """Run ``step_fn(*args)`` twice and require bit-identical outputs.

    Catches nondeterministic lowering/collectives and impure step functions.
    ``step_fn`` must not donate its inputs (donation would poison the second
    call); build an undonated step for the check.
    """
    out1 = jax.device_get(step_fn(*args))
    out2 = jax.device_get(step_fn(*args))
    leaves1, leaves2 = jax.tree.leaves(out1), jax.tree.leaves(out2)
    for i, (a, b) in enumerate(zip(leaves1, leaves2)):
        a = np.ascontiguousarray(np.asarray(a))
        b = np.ascontiguousarray(np.asarray(b))
        if a.dtype != b.dtype or a.shape != b.shape or a.tobytes() != b.tobytes():
            raise RuntimeError(
                f"{label} is nondeterministic: output leaf {i} differs "
                "between two identical invocations"
            )
