"""Corpus BLEU (Papineni et al. 2002) — the eval metric named by
BASELINE.json ("eval BLEU on src/tgt"); the reference computes no quality
metric beyond token accuracy, so this is net-new capability.

Standard definition: geometric mean of modified n-gram precisions (n≤4) with
brevity penalty; optional +1 smoothing on higher-order precisions (Lin & Och)
so short corpora don't zero out.
"""

from __future__ import annotations

import math
from collections import Counter


def _ngrams(tokens: list[str], n: int) -> Counter:
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def corpus_bleu(
    references: list[str] | list[list[str]],
    hypotheses: list[str] | list[list[str]],
    max_n: int = 4,
    smooth: bool = True,
) -> float:
    """BLEU in [0, 100]. Inputs are whitespace-tokenized automatically when
    given as strings. One reference per hypothesis (the bundled corpus is a
    single parallel file pair)."""
    if len(references) != len(hypotheses):
        raise ValueError("references and hypotheses must align")
    clipped = [0] * max_n
    totals = [0] * max_n
    ref_len = hyp_len = 0
    for ref, hyp in zip(references, hypotheses):
        ref_t = ref.split() if isinstance(ref, str) else list(ref)
        hyp_t = hyp.split() if isinstance(hyp, str) else list(hyp)
        ref_len += len(ref_t)
        hyp_len += len(hyp_t)
        for n in range(1, max_n + 1):
            hyp_ng = _ngrams(hyp_t, n)
            ref_ng = _ngrams(ref_t, n)
            totals[n - 1] += max(len(hyp_t) - n + 1, 0)
            clipped[n - 1] += sum(min(c, ref_ng[g]) for g, c in hyp_ng.items())
    if hyp_len == 0:
        return 0.0
    log_p = 0.0
    for n in range(max_n):
        c, t = clipped[n], totals[n]
        if smooth and n > 0:
            c, t = c + 1, t + 1
        if c == 0 or t == 0:
            return 0.0
        log_p += math.log(c / t)
    log_p /= max_n
    bp = 1.0 if hyp_len > ref_len else math.exp(1.0 - ref_len / max(hyp_len, 1))
    return 100.0 * bp * math.exp(log_p)
