"""Flight recorder: the last seconds of telemetry, always on, crash-proof.

The fleet heals itself (supervised respawn) but until now threw away the
one thing a postmortem needs — the dying replica's final events. A
:class:`FlightRecorder` keeps a bounded in-memory ring of the process's
most recent events, spans, and metric snapshots (tapped off
``Telemetry.emit``, so every kind rides automatically) and persists it as
one small JSON document at ``<metrics_jsonl>.flight.json``:

- **periodically** (``autodump_s``) — the only dump a SIGKILL leaves
  behind, and the one the Supervisor salvages into a
  ``route.postmortem`` event before recycling the slot;
- **on signal** (SIGTERM, chained to any prior handler);
- **on explicit request** — the replica wire protocol's ``dump`` control
  message, operators, tests;
- **on close** — a clean shutdown's final record.

Non-automatic dumps additionally emit a ``flight.dump`` event (auto dumps
do not: a 2 Hz cadence must not flood the log it is recording).

Design rules: stdlib-only, jax-free, lock-cheap (``record`` is one deque
append under a lock — deques are bounded, so memory never grows with
traffic), and exception-free toward the host process — an unwritable dump
path downgrades to a one-time stderr warning exactly like the EventLog.
``python -m transformer_tpu.obs postmortem`` merges flight records and
event logs back into one fleet timeline (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import collections
import json
import os
import signal as _signal
import sys
import threading
import time

#: Ring capacities: events is the working set a postmortem reads; spans
#: mirror it; snapshots are big (full registry dumps) so few are kept.
DEFAULT_CAPACITY = 256
DEFAULT_SNAPSHOTS = 8


class FlightRecorder:
    """Bounded last-N ring of events/spans/snapshots with durable dumps.

    ``path=None`` disables persistence (``dump`` still returns the record
    — the contract checks and in-process tests use this). ``emit`` is an
    optional ``(kind, **fields)`` callable for the ``flight.dump`` event;
    the emitting Telemetry taps this recorder, so the dump event itself
    lands in the ring too (harmless — it is the ring's newest entry).
    """

    def __init__(
        self,
        path: str | None,
        capacity: int = DEFAULT_CAPACITY,
        snapshots: int = DEFAULT_SNAPSHOTS,
        autodump_s: float = 0.0,
        registry=None,
        emit=None,
        source: str | None = None,
    ):
        self.path = path
        self.autodump_s = max(float(autodump_s), 0.0)
        self.source = source
        self._emit = emit
        self._events: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self._spans: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self._snapshots: collections.deque = collections.deque(
            maxlen=max(1, int(snapshots))
        )
        self._lock = threading.Lock()
        self._last_dump = float("-inf")
        self._broken = False
        self.recorded = 0
        self.dumps = 0
        self._m_depth = None
        if registry is not None:
            self._m_depth = registry.gauge(
                "flight_depth",
                "entries currently held in the flight-recorder ring",
            )

    # -- recording (the Telemetry.emit tap) ---------------------------------

    def record(self, kind: str, fields: dict) -> None:
        """Append one event to the right ring. Lock-cheap: build outside
        the lock, one deque append inside it."""
        entry = {"ts": fields.get("ts") or round(time.time(), 6),
                 "kind": kind, **fields}
        if kind == "trace.span":
            ring = self._spans
        elif kind == "metrics.snapshot":
            ring = self._snapshots
        else:
            ring = self._events
        with self._lock:
            ring.append(entry)
            self.recorded += 1
            depth = (
                len(self._events) + len(self._spans) + len(self._snapshots)
            )
        if self._m_depth is not None:
            self._m_depth.set(depth)

    def tap(self, emit):
        """Wrap an ``(kind, **fields)`` emit callable so every event is
        recorded here before being forwarded — how a bare EventLog or
        Tracer arms the recorder without a Telemetry bundle."""

        def tapped(kind, **fields):
            self.record(kind, fields)
            return emit(kind, **fields)

        tapped.__wrapped__ = emit
        return tapped

    def depth(self) -> int:
        with self._lock:
            return len(self._events) + len(self._spans) + len(self._snapshots)

    # -- dumping ------------------------------------------------------------

    def snapshot_record(self, reason: str = "request") -> dict:
        """The dump document: bounded, self-describing, one JSON object."""
        with self._lock:
            events = list(self._events)
            spans = list(self._spans)
            snapshots = list(self._snapshots)
            recorded = self.recorded
        record = {
            "ts": round(time.time(), 6),
            "reason": reason,
            "pid": os.getpid(),
            "recorded": recorded,
            "dumps": self.dumps,
            "events": events,
            "spans": spans,
            "snapshots": snapshots,
        }
        if self.source:
            record["source"] = self.source
        return record

    def dump(self, reason: str = "request") -> dict:
        """Persist the ring to ``path`` (atomic tmp + rename) and return
        the record. Non-``auto`` reasons emit a ``flight.dump`` event."""
        record = self.snapshot_record(reason)
        self.dumps += 1
        record["dumps"] = self.dumps
        if self.path and not self._broken:
            tmp = f"{self.path}.tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(record, f)
                os.replace(tmp, self.path)
            except OSError as e:
                # EventLog's downgrade contract: warn once, go quiet — the
                # observed process must never die because forensics broke.
                self._broken = True
                print(
                    f"obs: flight dump path {self.path} unwritable ({e}); "
                    "flight persistence disabled for this process",
                    file=sys.stderr,
                )
        if reason != "auto" and self._emit is not None:
            self._emit(
                "flight.dump", reason=reason, path=self.path,
                events=len(record["events"]), spans=len(record["spans"]),
                snapshots=len(record["snapshots"]),
            )
        return record

    def maybe_dump(self) -> bool:
        """Periodic autodump — the crash-durability path. Cheap when idle:
        one clock read and a compare."""
        if self.autodump_s <= 0:
            return False
        now = time.perf_counter()
        if now - self._last_dump < self.autodump_s:
            return False
        self._last_dump = now
        self.dump("auto")
        return True

    # -- signals ------------------------------------------------------------

    def install_signal_handlers(self, signums=(_signal.SIGTERM,)) -> None:
        """Dump on the given signals, then chain to the previous handler
        (SIG_DFL is re-raised so default termination semantics survive).
        Best-effort: off the main thread this is a silent no-op."""
        for signum in signums:
            try:
                prev = _signal.getsignal(signum)

                def handler(num, frame, _prev=prev):
                    self.dump("signal")
                    if callable(_prev):
                        _prev(num, frame)
                    elif _prev == _signal.SIG_DFL:
                        _signal.signal(num, _signal.SIG_DFL)
                        os.kill(os.getpid(), num)

                _signal.signal(signum, handler)
            except (ValueError, OSError):
                pass


def load_flight_record(path: str) -> dict | None:
    """Read one dump file; None (never an exception) when missing or torn
    — the Supervisor salvages best-effort from a process that just died."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and "events" in doc else None


def flight_path_for(metrics_jsonl: str) -> str:
    """The ONE definition of where a process's flight dumps live relative
    to its event log — the replica, the CLI flags, and the Supervisor's
    salvage must agree byte-for-byte."""
    return f"{metrics_jsonl}.flight.json"
