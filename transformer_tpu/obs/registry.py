"""Dependency-free metrics registry: counters, gauges, histograms.

The host-side metrics core of the obs subsystem (docs/OBSERVABILITY.md).
Deliberately NOT a Prometheus client-library clone: no label cardinality
machinery, no multiprocess files — one process, one registry, flat metric
names (``serve_slots_active``, ``train_tokens_per_sec_total``). What it does
promise:

- **Zero device interaction**: this module never imports jax/numpy — the
  telemetry-inert contract in ``analysis/contracts.py`` depends on recording
  being structurally unable to add device ops.
- **Cheap recording**: ``inc``/``set``/``observe`` are a few float ops under
  the GIL — safe to call once per scheduler step or train dispatch.
- **Three export shapes** from one source of truth: ``snapshot()`` (JSON
  for the event log / summarize CLI), ``to_prometheus_text()`` (text
  exposition v0.0.4 for a scrape or file), and per-histogram
  :class:`~transformer_tpu.obs.quantiles.StreamingHistogram` access (for the
  tfevents sink).
"""

from __future__ import annotations

import threading
import time

from transformer_tpu.obs.quantiles import StreamingHistogram

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(
            f"metric name {name!r} is not Prometheus-exposable: use "
            "[a-zA-Z_:][a-zA-Z0-9_:]*"
        )
    return name


class Counter:
    """Monotonically non-decreasing count."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        self.name, self.help = name, help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self.value += n


class Gauge:
    """A value that goes up and down (occupancy, backlog, bytes in use)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        self.name, self.help = name, help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Distribution with online p50/p95/p99 — a thin registry wrapper over
    :class:`StreamingHistogram`. Pass ``hist=`` to export an EXISTING
    StreamingHistogram (the StepTimer-reuse path: one sample stream, no
    duplicate accounting) instead of allocating a private one."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        hist: StreamingHistogram | None = None,
    ) -> None:
        self.name, self.help = name, help
        self.hist = hist if hist is not None else StreamingHistogram()

    def observe(self, value: float, n: int = 1) -> None:
        self.hist.observe(value, n)

    def quantile(self, q: float) -> float:
        return self.hist.quantile(q)


class MetricsRegistry:
    """Name-keyed get-or-create store for the three metric kinds.

    Threading contract (machine-checked: the TPA1xx concurrency rules lint
    this module, and ``analysis/schedules.py registry_scrape_vs_create``
    explores scrape-vs-lazy-creation interleavings — its revert-the-lock
    canary reproduces the pre-fix race): creation AND iteration take
    ``self._lock``, so the /metrics scrape thread can walk the registry
    while the observed loop lazily creates metrics. Recording on an
    already-created metric is plain float arithmetic — per-metric locks
    would cost more than the races they prevent, and every recorder in
    this repo is single-threaded per metric.
    """

    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        _check_name(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"wanted {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        hist: StreamingHistogram | None = None,
    ) -> Histogram:
        m = self._get_or_create(Histogram, name, help, hist=hist)
        if hist is not None and m.hist is not hist:
            raise ValueError(
                f"histogram {name!r} already bound to a different sample "
                "stream"
            )
        return m

    def __iter__(self):
        # Snapshot under the creation lock: the /metrics scrape handler
        # iterates from its own thread while the observed loop may still be
        # lazily creating metrics (first grouped batch, first epoch end) —
        # an unlocked dict walk there is a RuntimeError waiting for traffic.
        with self._lock:
            metrics = list(self._metrics.values())
        return iter(sorted(metrics, key=lambda m: m.name))

    def snapshot(self) -> dict:
        """JSON-able view of every metric — the payload of the periodic
        ``metrics.snapshot`` event the summarize CLI aggregates."""
        out: dict = {}
        for m in self:
            if isinstance(m, Histogram):
                out[m.name] = m.hist.snapshot()
            else:
                out[m.name] = m.value
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format v0.0.4. Histograms export the
        standard cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``
        triple, so a stock scraper computes the same quantiles we report."""
        lines: list[str] = []
        for m in self:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                cum = 0
                for bound, count in m.hist.buckets():
                    cum += count
                    lines.append(f'{m.name}_bucket{{le="{bound:.9g}"}} {cum}')
                lines.append(f'{m.name}_bucket{{le="+Inf"}} {m.hist.count}')
                lines.append(f"{m.name}_sum {m.hist.total:.9g}")
                lines.append(f"{m.name}_count {m.hist.count}")
            else:
                lines.append(f"{m.name} {m.value:.9g}")
        lines.append(f"# EOF generated {time.time():.3f}")
        return "\n".join(lines) + "\n"
