"""Structured JSONL event log.

One event per line: ``{"ts": <unix seconds>, "kind": "<dotted.kind>", ...}``.
The kinds this repo emits (schema in docs/OBSERVABILITY.md):

- ``serve.request`` — one per finished/errored request: the full span
  breakdown (queue/prefill/first-token/total seconds, token counts).
- ``serve.batch`` — one per grouped-path decode batch.
- ``train.window`` — one per closed StepTimer window (log/eval/epoch
  boundary): steps, tokens, throughput, loss/accuracy/grad-norm.
- ``train.memory`` / ``train.compile`` — device memory stats and jit
  compile-cache accounting at epoch boundaries.
- ``trace.span`` — one per CLOSED tracing span (``obs/trace.py``):
  ``trace``/``span``/``parent`` lineage, ``name``, ``lane``, start ``t0``
  and ``dur_s``. Export with ``python -m transformer_tpu.obs trace``.
- ``slo.burn`` — one per SLO breach-state transition (``obs/slo.py``):
  ``name``, ``breached``, per-window burn rates.
- ``serve.retry`` — one per transient-admission retry: ``order``,
  ``attempt``, ``backoff_ms``, the fault, and the victim's ``trace`` id
  when tracing is on.
- ``route.dispatch`` / ``route.failover`` / ``route.revive`` — the
  multi-replica router's events (``serve/router.py``): per-request
  dispatch decisions (replica, policy, redispatch count, ``trace``),
  replica failures with the victim orders + trace ids, and half-open
  breaker revivals of heartbeat-timeout victims; ``obs summarize
  --merge`` reports per-replica request share and redispatches from
  these.
- ``route.spawn`` / ``route.retire`` / ``route.scale`` — the supervision
  tier (``serve/supervisor.py``): replica (re)spawn admissions
  (``heal_s`` death-to-admitted, ``warmed_tokens`` prefix-cache warm-up;
  ``gave_up=true`` when a crash loop exhausts its restart budget),
  drain-and-retire completions, and every autoscaling decision with the
  SLO burn-rate evidence window that justified it (``direction``,
  ``signal``, ``burn_rate``, ``evidence``). ``obs summarize --merge``
  renders the fleet section from these.
- ``route.intake`` / ``route.answered`` / ``route.hb`` — the primary
  router's HA journal (``--ha``; ``serve/standby.py`` tails these): one
  replayable intake record per accepted order (request, traceparent,
  remaining deadline budget), delivery marks from ``drain_ready``, and
  the periodic liveness beacon (authority ``epoch``, replica control
  ``ports``). An adopting router re-journals the orders it adopted, so
  chained takeovers replay from its log alone.
- ``route.takeover`` — emitted once by an adopting standby: the new
  ``epoch``, adopted/failed replicas, and how every undelivered order
  was resolved (recovered / re-owned / re-dispatched).
- ``route.mesh_mismatch`` — the Supervisor refused a spawned replica
  whose ``ready`` line reported a mesh shape different from the fleet's
  ``expected_mesh`` (``expected``, ``got``): the link is killed, the
  attempt counts as a spawn failure, and respawn backoff applies — a
  heal can never silently downgrade a sharded replica
  (docs/SERVING.md "Sharded replicas").
- ``route.upgrade`` / ``route.canary`` — the live-weights control plane
  (``serve/upgrade.py``): rollout lifecycle events tagged by ``phase``
  (``started``/``swapped``/``completed``/``rejected``/``failed``/
  ``rolled_back``) carrying the target ``version`` (checkpoint manifest
  digest), per-replica quiesce/swap seconds, ``time_to_upgrade_s``, and —
  on a rollback — ``rolled_back=true`` with the per-window burn
  ``evidence`` that triggered it; canary lifecycle (``started``/
  ``promoted``) with the pinned slice (``every``), window, and request
  count. ``route.dispatch`` additionally carries each dispatch's
  ``weight_version``, so ``obs summarize --merge`` renders the upgrade
  section (per-version request share, canary window, rollbacks,
  time-to-upgrade) from the same stream.
- ``route.postmortem`` — emitted by the Supervisor when it captures a
  dead or respawning replica's final flight record (``obs/flight.py``)
  before recycling the slot: ``replica``, ``origin`` (``wire`` for a
  live ``dump`` reply, ``file`` for an on-disk autodump salvaged after a
  SIGKILL), and the full ``record`` (events/spans/snapshots rings).
  ``python -m transformer_tpu.obs postmortem`` reconstructs the fleet's
  last seconds from these.
- ``flight.dump`` — one per non-automatic flight-recorder dump
  (signal / explicit request / clean close; periodic autodumps stay
  silent): ``reason``, ``path``, and ring sizes.
- ``perf.drift`` — one per measured-vs-banked breach-state transition
  (``obs/profile.py``): ``program``, measured-over-banked p50 ``ratio``,
  the ``band``, both p50s, and ``breached``. Same transition-only
  discipline as ``slo.burn``.
- ``metrics.snapshot`` — periodic full registry dump (histograms as
  count/sum/min/max/p50/p95/p99).
- ``bench.relay_probe`` / ``bench.fallback_row`` / ``bench.attempt`` —
  bench-infra attribution (bench.py), so a flaky relay is diagnosable from
  the log after the fact.

The machine-readable mirror of this list is :data:`EVENT_CATALOGUE`
below; a tier-1 AST sweep (tests/test_perf_observatory.py) fails if any
``emit`` call site in the package uses a kind missing from the catalogue
or from docs/OBSERVABILITY.md — the catalogue cannot silently rot.

Threading contract (machine-checked: the TPA1xx concurrency rules lint
this module, ``analysis/schedules.py eventlog_writers`` explores
concurrent-emit interleavings, and tests/test_obs.py hammers it with real
threads): ``emit`` is MULTI-WRITER SAFE. One lock serializes every write —
the serve CLI's scrape/flush threads, scheduler spans, and bench
attribution can share one log and two events can never interleave bytes
within a line (each line parses back as one JSON object). The
``_broken``-sink state transitions under the same lock, so concurrent
writers hitting a dead disk produce exactly one stderr warning. A full
disk must never kill the process being observed: OSError on write
downgrades to that warning and the log goes quiet — telemetry is an
instrument, not a dependency.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time

# Fault-injection slot: ``serve.resilience.install`` plants the plane's
# hook here (and clears it on uninstall) so the ``obs.emit`` chaos point
# works WITHOUT this module importing resilience — obs stays jax-free and
# serve-free by import structure (test-pinned). The injected exception
# subclasses OSError on purpose: it flows through the same handler a full
# disk would.
fault_hook = None

#: Every event kind this package emits, with a one-line meaning. The
#: catalogue drift gate (tests/test_perf_observatory.py) AST-sweeps all
#: literal ``emit(kind, ...)`` call sites and asserts each kind appears
#: here AND in docs/OBSERVABILITY.md — add the entry (and the doc schema)
#: in the same change that adds an emit site.
EVENT_CATALOGUE = {
    "bench.attempt": "bench-infra: one per relay attempt (bench.py rows)",
    "bench.fallback_row": "bench-infra: CPU-fallback row attribution",
    "bench.no_value": "bench-infra: a probe that produced no value",
    "bench.relay_probe": "bench-infra: relay liveness probe outcome",
    "ckpt.fallback": "trainer restored an older checkpoint after a bad one",
    "flight.dump": "non-automatic flight-recorder dump (signal/request/close)",
    "metrics.snapshot": "periodic full metrics-registry dump",
    "perf.drift": "measured p50 left (or re-entered) its banked band",
    "route.answered": "HA journal: delivery mark for an accepted order",
    "route.canary": "canary slice lifecycle (started/promoted)",
    "route.dispatch": "router picked a replica for one request",
    "route.failover": "replica failure with victim orders re-dispatched",
    "route.hb": "HA journal: periodic primary liveness beacon",
    "route.intake": "HA journal: one replayable accepted-order record",
    "route.mesh_mismatch": "respawned replica reported the wrong mesh shape",
    "route.postmortem": "supervisor captured a dead replica's flight record",
    "route.retire": "supervised drain-and-retire completed",
    "route.revive": "half-open breaker revived a heartbeat-timeout victim",
    "route.scale": "autoscaling decision with its burn-rate evidence",
    "route.spawn": "replica (re)spawn admitted (or crash loop gave up)",
    "route.takeover": "standby adopted the fleet under a new epoch",
    "route.upgrade": "live-weights rollout lifecycle (by phase)",
    "schedules.test": "interleaving explorer's synthetic event (self-test)",
    "serve.batch": "one grouped-path decode batch",
    "serve.breaker": "admission circuit-breaker state transition",
    "serve.request": "one finished/errored request with span breakdown",
    "serve.retry": "one transient-admission retry",
    "slo.burn": "SLO breach-state transition with window burn rates",
    "trace.span": "one closed tracing span",
    "train.compile": "jit compile-cache accounting at an epoch boundary",
    "train.eval": "one eval pass result",
    "train.memory": "device memory stats at an epoch boundary",
    "train.predicted": "cost-model prediction snapshot for the train step",
    "train.preempt": "preemption checkpoint written on signal",
    "train.window": "one closed StepTimer throughput window",
}


class EventLog:
    """Append-only JSONL event writer.

    ``breaker`` (optional, duck-typed ``serve.resilience.CircuitBreaker``)
    upgrades the permanent ``_broken`` downgrade to the graceful-degradation
    ladder: K consecutive write failures OPEN the sink (events dropped,
    one stderr warning per outage), a cooldown later one half-open emit
    re-probes the disk, and success closes the breaker — a transiently
    full disk costs an outage window, not the rest of the process's
    telemetry. Without a breaker the historical contract holds: first
    failure disables the sink for good, with exactly one warning.
    """

    def __init__(
        self, path_or_file: "str | io.TextIOBase", breaker=None
    ) -> None:
        self._lock = threading.Lock()
        self._broken = False
        self._breaker = breaker
        if isinstance(path_or_file, str):
            d = os.path.dirname(os.path.abspath(path_or_file))
            os.makedirs(d, exist_ok=True)
            self._file = open(path_or_file, "a", buffering=1)
            self.path: str | None = path_or_file
            self._owns = True
        else:
            self._file = path_or_file
            self.path = getattr(path_or_file, "name", None)
            self._owns = False

    def emit(self, kind: str, **fields) -> None:
        """Append one event. ``fields`` must be JSON-serializable; a ``ts``
        stamp is added unless the caller supplies one (bench.py backfills).
        Safe to call from any thread: the line is serialized outside the
        lock, the single ``write`` happens inside it."""
        if self._broken:
            # Racy fast path — a dead sink must not keep paying json.dumps
            # per emit; the authoritative re-check happens under the lock.
            return
        if self._breaker is not None and not self._breaker.allow():
            return  # sink open: drop quietly until the cooldown re-probe
        event = {"ts": fields.pop("ts", None) or round(time.time(), 6),
                 "kind": kind, **fields}
        line = json.dumps(event, sort_keys=False)
        try:
            with self._lock:
                if self._broken:
                    return
                if fault_hook is not None:
                    fault_hook("obs.emit")  # raises an OSError-shaped fault
                self._file.write(line + "\n")
        except (OSError, ValueError):  # ValueError: write to a closed file
            if self._breaker is not None:
                self._record_sink_failure()
                return
            if self._mark_broken():
                print(
                    f"obs: event log {self.path or '<stream>'} unwritable; "
                    "telemetry disabled for this process",
                    file=sys.stderr,
                )
        else:
            if self._breaker is not None:
                self._breaker.record_success()

    def _record_sink_failure(self) -> None:
        """Feed the breaker; warn exactly when this failure OPENS it (one
        warning per outage, whichever of emit/flush trips it)."""
        if self._breaker.record_failure():
            print(
                f"obs: event log {self.path or '<stream>'} unwritable; "
                "sink open (will re-probe after cooldown)",
                file=sys.stderr,
            )

    def _mark_broken(self) -> bool:
        """Flip the sink dead under the lock; True for exactly one caller
        (so N concurrent writers racing a dead disk warn once, not N
        times)."""
        with self._lock:
            was = self._broken
            self._broken = True
            return not was

    def flush(self) -> None:
        try:
            with self._lock:
                if self._broken:
                    return
                self._file.flush()
        except (OSError, ValueError):
            if self._breaker is not None:
                # A flush can be the fault that OPENS the sink; without the
                # shared warn-on-trip the outage would start silently
                # (emit()'s allow() short-circuits before any write).
                self._record_sink_failure()
            else:
                self._mark_broken()

    def close(self) -> None:
        self.flush()
        if self._owns:
            try:
                self._file.close()
            except OSError:
                pass


def read_events(path: str, kind: str | None = None) -> list[dict]:
    """Load a JSONL event log; malformed lines (a crash mid-write) are
    skipped, never fatal — the summarize CLI must work on truncated logs."""
    out: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict) and (kind is None or ev.get("kind") == kind):
                out.append(ev)
    return out
