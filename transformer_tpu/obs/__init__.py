"""transformer_tpu.obs — unified telemetry.

A dependency-free (stdlib-only) observability core: a metrics registry
(counters / gauges / histograms with online p50/p95/p99), a structured JSONL
event log, and three sinks — JSONL, Prometheus text exposition (file and/or
``/metrics`` endpoint), and the ``utils/tensorboard.py`` tfevents writer.
``python -m transformer_tpu.obs summarize <jsonl>`` renders a run report.

Import rule: nothing under ``transformer_tpu.obs`` may import jax or numpy.
Telemetry records host-side scalars at existing sync points; keeping the
package structurally device-free is what makes the ``telemetry_inert``
contract (``analysis/contracts.py``) and the serving byte-identity guarantee
cheap to uphold. See docs/OBSERVABILITY.md.
"""

from transformer_tpu.obs.events import EventLog, read_events
from transformer_tpu.obs.merge import filter_events, merge_events
from transformer_tpu.obs.quantiles import StreamingHistogram
from transformer_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from transformer_tpu.obs.slo import (
    DEFAULT_SLOS,
    SLOEngine,
    SLOSpec,
    evaluate_slos,
    parse_slo_spec,
)
from transformer_tpu.obs.telemetry import (
    Telemetry,
    device_memory_stats,
    timed_call,
)
from transformer_tpu.obs.trace import (
    SpanContext,
    Tracer,
    chrome_trace,
    traced_call,
)

__all__ = [
    "Counter",
    "DEFAULT_SLOS",
    "EventLog",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SLOEngine",
    "SLOSpec",
    "SpanContext",
    "StreamingHistogram",
    "Telemetry",
    "Tracer",
    "chrome_trace",
    "device_memory_stats",
    "evaluate_slos",
    "filter_events",
    "merge_events",
    "parse_slo_spec",
    "read_events",
    "timed_call",
    "traced_call",
]
