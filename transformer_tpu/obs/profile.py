"""Per-program dispatch profiler: the MEASURED half of the roofline story.

The cost model (``analysis/costs.py``) predicts FLOPs / ``bytes_moved`` /
peak bytes for every canned jitted program the serving and training paths
dispatch; this module clocks those same programs as they actually run and
joins the two sides. The TPU relay being down makes the measured side the
only evidence a landed kernel did not silently regress wall-clock —
prediction alone cannot notice a slow program that still moves the
predicted bytes.

Three surfaces:

- :class:`ProgramProfiler` — per-program
  :class:`~transformer_tpu.obs.quantiles.StreamingHistogram` of dispatch
  wall seconds plus a token counter, registry-bound as
  ``perf_seconds_<program>`` / ``perf_tokens_total_<program>`` so the
  samples ride every ``metrics.snapshot`` event and Prometheus exposition
  for free. Derived ``perf_measured_*`` gauges (tokens/s, p50 ms,
  effective bytes/s, roofline ratio) and a ``perf_drift_<program>`` gauge
  (measured p50 over the banked baseline p50) refresh as samples arrive;
  a ``perf.drift`` event fires on each banked-band breach-state
  TRANSITION (never per sample — same discipline as ``slo.burn``).
- the banked baseline (``obs/roofline_baseline.json``, checked in):
  per-program p50 seconds + an acceptance band, plus the predictions
  (``bytes_moved``, ``tokens_per_step``) frozen at bank time and the
  host's assumed peak HBM bandwidth. ``obs roofline --update`` rewrites
  it from a measured episode — the same pass → perturb → fail →
  ``--update`` → pass workflow as the analysis baseline families.
- :func:`roofline_report` — the offline join (``obs roofline``): measured
  per-program histograms recovered from a JSONL episode's
  ``metrics.snapshot`` stream against an ``analysis costs --format=json``
  document, tolerant when either side is absent.

Design rules (the obs package's): stdlib-only, jax-free, host-side at
existing sync points. :func:`profile_call` is the wrapper sibling of
``obs.telemetry.timed_call`` / ``obs.trace.traced_call`` with the same
inertness obligation — the ``telemetry_inert`` contract traces the pool
step, slot prefill, and verify programs through it and pins byte-identical
jaxprs; the retrace sentinel keeps steady-state recompiles at 0 with the
profiler armed.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

from transformer_tpu.obs.quantiles import StreamingHistogram

#: The canned jitted programs the scheduler/trainer dispatch, named with
#: the SAME base names the cost model's reports use (variant brackets
#: stripped) — the join key between measurement and prediction.
CANNED_PROGRAMS = (
    "serve.pool_step",
    "serve.pool_step_paged",
    "serve.pool_step_paged_flash",
    "serve.pool_verify",
    "serve.pool_verify_paged",
    "serve.pool_verify_paged_flash",
    "serve.slot_prefill",
    "serve.slot_prefill_paged",
    "serve.slot_restore",
    "train.step",
)

#: Fallback peak HBM bandwidth for the roofline denominator when the
#: baseline file does not bank one: TPU v5 lite (the last hardware the
#: relay measured — ROADMAP's banked train row) moves ~819 GB/s. The repo
#: has no machine model; the honest number lives in the baseline file
#: (``peak_bytes_per_s``) where ``--update`` runs can override it per host.
DEFAULT_PEAK_BYTES_PER_S = 8.19e11

#: Default drift acceptance band, as [lo, hi] multipliers on the banked
#: p50: generous on purpose — CPU CI boxes jitter, and the band exists to
#: catch a silently-landed 10x regression, not 20% scheduler noise.
DEFAULT_BAND = (0.2, 5.0)

#: Samples a program must accumulate before its p50 is judged against the
#: band (a single cold dispatch is compile + run, not steady state).
MIN_DRIFT_SAMPLES = 8

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "roofline_baseline.json"
)

_SECONDS_PREFIX = "perf_seconds_"
_TOKENS_PREFIX = "perf_tokens_total_"


def metric_suffix(program: str) -> str:
    """Program name -> the registry-legal metric suffix
    (``serve.pool_step`` -> ``serve_pool_step``; dots are the only
    character the canned names carry outside the metric charset)."""
    return program.replace(".", "_")


_SUFFIX_TO_PROGRAM = {metric_suffix(p): p for p in CANNED_PROGRAMS}


def program_for_suffix(suffix: str) -> str:
    """Reverse of :func:`metric_suffix` for the canned set; unknown
    suffixes pass through unchanged (the report still rows them)."""
    return _SUFFIX_TO_PROGRAM.get(suffix, suffix)


# --------------------------------------------------------------------------
# baseline bank

def load_baseline(path: str | None = None) -> dict:
    """The banked baseline document, ``{}`` when missing or unreadable —
    the profiler and the report degrade to measured-only, never error."""
    try:
        with open(path or BASELINE_PATH, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    return doc if isinstance(doc, dict) else {}


def write_baseline(
    path: str,
    measured: dict,
    predictions: dict | None = None,
    peak_bytes_per_s: float | None = None,
    band=DEFAULT_BAND,
) -> dict:
    """Bank ``measured`` (program -> row with ``p50_s``) as the new
    baseline, freezing each program's predictions (``bytes_moved``,
    ``tokens_per_step``) next to its band. Atomic (tmp + rename), like
    every other checked-in baseline writer."""
    programs = {}
    for name in sorted(measured):
        row = measured[name]
        p50 = row.get("p50_s")
        if not isinstance(p50, (int, float)) or p50 <= 0:
            continue
        entry = {"p50_s": round(float(p50), 9), "band": list(band)}
        pred = (predictions or {}).get(name) or {}
        if pred.get("bytes_moved"):
            entry["bytes_moved"] = int(pred["bytes_moved"])
        extras = pred.get("extras") or {}
        tps = extras.get("tokens_per_step") or pred.get("tokens_per_step")
        if tps:
            entry["tokens_per_step"] = int(tps)
        programs[name] = entry
    doc = {
        "peak_bytes_per_s": float(peak_bytes_per_s or DEFAULT_PEAK_BYTES_PER_S),
        "programs": programs,
        "note": (
            "Banked by `obs roofline --update`: per-program measured p50 "
            "seconds + acceptance band [lo, hi] (multipliers on p50); "
            "bytes_moved/tokens_per_step frozen from the cost model at "
            "bank time. Absolute times are per-host — re-bank on the box "
            "that enforces the band."
        ),
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def predictions_by_program(costs) -> dict:
    """Index an ``analysis costs --format=json`` document (or its
    ``programs`` list) by BASE program name, stripping the ``[variant,...]``
    suffix; when several variants share a base the ``lm_bf16`` one wins
    (the default serving config, the one the profiler actually times)."""
    reports = costs.get("programs", []) if isinstance(costs, dict) else list(costs or [])
    out: dict = {}
    for r in reports:
        if not isinstance(r, dict):
            continue
        name = str(r.get("name") or "")
        base = name.split("[", 1)[0]
        if not base:
            continue
        prev = out.get(base)
        if prev is None or (
            "[lm_bf16" in name and "[lm_bf16" not in str(prev.get("name", ""))
        ):
            out[base] = r
    return out


# --------------------------------------------------------------------------
# the profiler

class _ProgramStream:
    __slots__ = (
        "hist", "tokens", "in_band", "m_tokens", "m_tokens_per_s",
        "m_p50_ms", "m_bytes_per_s", "m_roofline", "m_drift",
    )

    def __init__(self):
        self.hist = StreamingHistogram()
        self.tokens = 0.0
        self.in_band: bool | None = None  # None = not yet judged
        self.m_tokens = None
        self.m_tokens_per_s = None
        self.m_p50_ms = None
        self.m_bytes_per_s = None
        self.m_roofline = None
        self.m_drift = None


class ProgramProfiler:
    """Clock every dispatch of the canned programs into per-program
    histograms; export measured gauges; sentinel measured-vs-banked drift.

    ``record`` is the hot-path surface: one ``observe`` + a token add,
    with the derived gauges refreshed every ``refresh_every``-th sample
    (quantile extraction walks the histogram buckets — not free at
    per-step cadence). All host-side, jax-free, exception-free.
    """

    def __init__(
        self,
        registry=None,
        emit=None,
        baseline: dict | None = None,
        min_samples: int = MIN_DRIFT_SAMPLES,
        refresh_every: int = 8,
    ):
        self._registry = registry
        self._emit = emit
        self._lock = threading.Lock()
        self._streams: dict[str, _ProgramStream] = {}
        doc = load_baseline() if baseline is None else (baseline or {})
        self.baseline = doc.get("programs", {}) if isinstance(doc, dict) else {}
        self.peak_bytes_per_s = float(
            (doc.get("peak_bytes_per_s") if isinstance(doc, dict) else None)
            or DEFAULT_PEAK_BYTES_PER_S
        )
        self.min_samples = max(1, int(min_samples))
        self.refresh_every = max(1, int(refresh_every))
        self.stats = {"records": 0, "drift_events": 0}

    # -- recording ----------------------------------------------------------

    def _stream(self, program: str) -> _ProgramStream:
        s = self._streams.get(program)
        if s is not None:
            return s
        with self._lock:
            s = self._streams.get(program)
            if s is None:
                s = _ProgramStream()
                if self._registry is not None:
                    suffix = metric_suffix(program)
                    reg = self._registry
                    reg.histogram(
                        _SECONDS_PREFIX + suffix,
                        f"measured dispatch seconds for {program}",
                        hist=s.hist,
                    )
                    s.m_tokens = reg.counter(
                        _TOKENS_PREFIX + suffix,
                        f"tokens processed by {program} dispatches",
                    )
                    s.m_tokens_per_s = reg.gauge(
                        f"perf_measured_tokens_per_s_{suffix}",
                        f"measured tokens/s for {program}",
                    )
                    s.m_p50_ms = reg.gauge(
                        f"perf_measured_p50_ms_{suffix}",
                        f"measured p50 dispatch ms for {program}",
                    )
                    if self._banked(program).get("bytes_moved"):
                        s.m_bytes_per_s = reg.gauge(
                            f"perf_measured_bytes_per_s_{suffix}",
                            f"effective bytes/s for {program} (predicted "
                            "bytes_moved over measured p50)",
                        )
                        s.m_roofline = reg.gauge(
                            f"perf_roofline_ratio_{suffix}",
                            f"effective over peak bytes/s for {program}",
                        )
                    if self._banked(program).get("p50_s"):
                        s.m_drift = reg.gauge(
                            f"perf_drift_{suffix}",
                            f"measured p50 over banked p50 for {program}",
                        )
                self._streams[program] = s
        return s

    def _banked(self, program: str) -> dict:
        entry = self.baseline.get(program)
        return entry if isinstance(entry, dict) else {}

    def record(self, program: str, seconds: float, tokens: float = 0) -> None:
        """One dispatch of ``program`` took ``seconds`` and processed
        ``tokens`` tokens (0 when the caller has no honest count)."""
        s = self._stream(program)
        s.hist.observe(max(float(seconds), 0.0))
        self.stats["records"] += 1
        if tokens:
            s.tokens += tokens
            if s.m_tokens is not None:
                s.m_tokens.inc(tokens)
        count = s.hist.count
        if count % self.refresh_every == 0 or count == self.min_samples:
            self._refresh(program, s)

    def _refresh(self, program: str, s: _ProgramStream) -> None:
        snap = s.hist.snapshot()
        p50 = snap.get("p50")
        if not p50 or p50 <= 0:
            return
        if s.m_p50_ms is not None:
            s.m_p50_ms.set(p50 * 1e3)
        total_s = snap.get("sum") or 0.0
        if s.m_tokens_per_s is not None and total_s > 0:
            s.m_tokens_per_s.set(s.tokens / total_s)
        bank = self._banked(program)
        bytes_moved = bank.get("bytes_moved")
        if bytes_moved and s.m_bytes_per_s is not None:
            eff = bytes_moved / p50
            s.m_bytes_per_s.set(eff)
            if s.m_roofline is not None:
                s.m_roofline.set(eff / self.peak_bytes_per_s)
        base_p50 = bank.get("p50_s")
        if base_p50 and snap.get("count", 0) >= self.min_samples:
            ratio = p50 / base_p50
            if s.m_drift is not None:
                s.m_drift.set(ratio)
            lo, hi = tuple(bank.get("band") or DEFAULT_BAND)
            in_band = lo <= ratio <= hi
            if s.in_band is not None and in_band != s.in_band and self._emit:
                # Breach-state TRANSITION only (slo.burn's discipline): a
                # drifting soak must not flood its own log.
                self.stats["drift_events"] += 1
                self._emit(
                    "perf.drift", program=program,
                    ratio=round(ratio, 4), band=[lo, hi],
                    measured_p50_s=round(p50, 9),
                    baseline_p50_s=round(base_p50, 9),
                    breached=not in_band,
                )
            elif s.in_band is None and not in_band and self._emit:
                self.stats["drift_events"] += 1
                self._emit(
                    "perf.drift", program=program,
                    ratio=round(ratio, 4), band=[lo, hi],
                    measured_p50_s=round(p50, 9),
                    baseline_p50_s=round(base_p50, 9),
                    breached=True,
                )
            s.in_band = in_band

    # -- reading ------------------------------------------------------------

    def summary(self) -> dict:
        """program -> measured row (the benchmarks' and tests' surface):
        ``dispatches`` / ``p50_ms`` / ``p95_ms`` / ``p50_s`` / ``tokens``
        / ``tokens_per_s``, plus ``drift`` when the program is banked."""
        out = {}
        with self._lock:
            streams = dict(self._streams)
        for program, s in sorted(streams.items()):
            snap = s.hist.snapshot()
            if not snap.get("count"):
                continue
            p50 = snap.get("p50") or 0.0
            total_s = snap.get("sum") or 0.0
            row = {
                "program": program,
                "dispatches": snap["count"],
                "p50_s": p50,
                "p50_ms": round(p50 * 1e3, 6),
                "p95_ms": round((snap.get("p95") or 0.0) * 1e3, 6),
                "tokens": s.tokens,
                "tokens_per_s": (
                    round(s.tokens / total_s, 3) if total_s > 0 else None
                ),
            }
            bank = self._banked(program)
            if bank.get("p50_s") and p50 > 0:
                row["drift"] = round(p50 / bank["p50_s"], 4)
            if bank.get("bytes_moved") and p50 > 0:
                row["effective_bytes_per_s"] = bank["bytes_moved"] / p50
                row["roofline_ratio"] = round(
                    row["effective_bytes_per_s"] / self.peak_bytes_per_s, 6
                )
            out[program] = row
        return out


def profile_call(
    fn: Callable, profiler: ProgramProfiler, program: str, tokens: float = 0
) -> Callable:
    """Wrap ``fn`` so each call's wall time lands in ``profiler`` under
    ``program`` (``tokens`` credited per call). Third sibling of
    ``timed_call`` / ``traced_call`` with the identical inertness
    obligation, pinned by the ``telemetry_inert`` contract: when ``fn`` is
    jitted the wrapper runs outside its trace, and traced directly it
    forwards outputs untouched — byte-identical jaxprs."""

    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        profiler.record(program, time.perf_counter() - t0, tokens=tokens)
        return out

    wrapped.__wrapped__ = fn
    return wrapped


# --------------------------------------------------------------------------
# the offline join (obs roofline)

def measured_from_events(events: list) -> dict:
    """Recover per-program measured rows from a JSONL episode: the LAST
    ``metrics.snapshot`` carrying each ``perf_seconds_*`` histogram wins
    (registry metrics are cumulative, so the last snapshot is the
    episode's total)."""
    hists: dict[str, dict] = {}
    tokens: dict[str, float] = {}
    for e in events:
        if e.get("kind") != "metrics.snapshot":
            continue
        metrics = e.get("metrics")
        if not isinstance(metrics, dict):
            continue
        for name, value in metrics.items():
            if name.startswith(_SECONDS_PREFIX) and isinstance(value, dict):
                program = program_for_suffix(name[len(_SECONDS_PREFIX):])
                hists[program] = value
            elif name.startswith(_TOKENS_PREFIX) and isinstance(
                value, (int, float)
            ):
                program = program_for_suffix(name[len(_TOKENS_PREFIX):])
                tokens[program] = float(value)
    out = {}
    for program, snap in hists.items():
        if not snap.get("count"):
            continue
        p50 = snap.get("p50") or 0.0
        total_s = snap.get("sum") or 0.0
        toks = tokens.get(program, 0.0)
        out[program] = {
            "dispatches": snap.get("count", 0),
            "p50_s": p50,
            "p50_ms": round(p50 * 1e3, 6),
            "p95_ms": round((snap.get("p95") or 0.0) * 1e3, 6),
            "tokens": toks,
            "measured_tokens_per_s": (
                round(toks / total_s, 3) if total_s > 0 and toks else None
            ),
        }
    return out


def roofline_report(
    events: list, costs=None, baseline: dict | None = None
) -> dict:
    """Join a JSONL episode's measured programs against cost-model
    predictions and the banked baseline. Tolerant by construction: a
    missing prediction drops the bytes columns from that row, a missing
    bank drops the drift columns, an empty episode returns zero rows."""
    doc = load_baseline() if baseline is None else (baseline or {})
    banked = doc.get("programs", {}) if isinstance(doc, dict) else {}
    peak = float(
        (doc.get("peak_bytes_per_s") if isinstance(doc, dict) else None)
        or DEFAULT_PEAK_BYTES_PER_S
    )
    predicted = predictions_by_program(costs) if costs else {}
    measured = measured_from_events(events)
    rows = []
    for program in sorted(measured):
        m = measured[program]
        row = {"program": program, **m}
        pred = predicted.get(program) or {}
        bank = banked.get(program) if isinstance(banked, dict) else None
        bank = bank if isinstance(bank, dict) else {}
        bytes_moved = pred.get("bytes_moved") or bank.get("bytes_moved")
        extras = pred.get("extras") or {}
        tps = (
            extras.get("tokens_per_step")
            or pred.get("tokens_per_step")
            or bank.get("tokens_per_step")
        )
        p50 = m.get("p50_s") or 0.0
        if bytes_moved and p50 > 0:
            row["predicted_bytes_moved"] = int(bytes_moved)
            row["effective_bytes_per_s"] = bytes_moved / p50
            row["roofline_ratio"] = round(
                row["effective_bytes_per_s"] / peak, 6
            )
        if tps and p50 > 0:
            row["predicted_tokens_per_s"] = round(tps / p50, 3)
            mtps = m.get("measured_tokens_per_s")
            if mtps:
                row["measured_over_predicted_tokens"] = round(
                    mtps / row["predicted_tokens_per_s"], 4
                )
        if bank.get("p50_s") and p50 > 0:
            lo, hi = tuple(bank.get("band") or DEFAULT_BAND)
            row["drift"] = round(p50 / bank["p50_s"], 4)
            row["band"] = [lo, hi]
            row["in_band"] = lo <= row["drift"] <= hi
        rows.append(row)
    return {"peak_bytes_per_s": peak, "programs": rows}


def band_breaches(report: dict) -> list:
    """Rows whose measured p50 left their banked band (the ``--check``
    verdict): unbanked rows never breach — the band only judges what was
    deliberately banked."""
    return [
        r for r in report.get("programs", [])
        if r.get("in_band") is False
    ]


def roofline_ratio(
    bytes_moved: float, p50_s: float, peak_bytes_per_s: float | None = None
) -> float | None:
    """effective bytes/s over peak bytes/s for one program — the single
    definition the benchmarks and the report share."""
    if not bytes_moved or not p50_s or p50_s <= 0:
        return None
    peak = peak_bytes_per_s or float(
        load_baseline().get("peak_bytes_per_s") or DEFAULT_PEAK_BYTES_PER_S
    )
    return round((bytes_moved / p50_s) / peak, 6)
