"""Request-scoped distributed tracing: hierarchical spans over the event log.

The metrics registry answers "how is the fleet doing"; this module answers
"where did THIS request's latency go". A :class:`Tracer` mints spans with
``trace_id`` / ``span_id`` / ``parent_id`` lineage and emits one
``trace.span`` event per CLOSED span onto the existing JSONL
:class:`~transformer_tpu.obs.events.EventLog` — no second sink, no second
file format, and ``obs summarize`` keeps working on a traced log unchanged.
:func:`chrome_trace` converts any such log into the Chrome trace-event JSON
that chrome://tracing and Perfetto load (``python -m transformer_tpu.obs
trace <jsonl> --out trace.json``), one lane per serve slot plus
scheduler/intake/train lanes.

Design rules (the same ones the rest of obs lives by):

- **Stdlib-only, jax-free.** Spans are host wall-clock bookkeeping; nothing
  here may touch device values. The ``telemetry_inert`` contract
  (``analysis/contracts.py``) pins that a :func:`traced_call`-wrapped jitted
  function traces to a byte-identical jaxpr, and tests pin byte-identical
  serve answers and 0 steady-state recompiles with tracing enabled.
- **Emit on close.** One event per span, written when the span ends (with
  its start time ``t0`` and duration ``dur_s``), so the log stays
  append-only and a crash loses only the spans still open — the exporter
  and the span-tree tests treat an unclosed span as a defect, and
  ``Tracer.open_count`` makes "every opened span closes exactly once"
  directly assertable.
- **Context crosses processes.** :class:`SpanContext` serializes to the
  W3C ``traceparent`` form (``00-<trace>-<span>-01``); a request dict may
  carry ``"traceparent"`` and the scheduler adopts it as the root parent,
  so the future multi-replica router tier propagates trace lineage for
  free and a cross-file merge (``obs/merge.py``) can re-join one request's
  spans across replica logs — and estimate per-file clock skew from them.

Parenting: ``tracer.span(...)`` (the context-manager form) keeps a
per-thread current-span stack, so nested ``with`` blocks — and any
:func:`traced_call`-wrapped function invoked inside them — parent
automatically. Long-lived spans that outlive a call frame (a serve
request's lifecycle across many scheduler steps) use ``start_span`` /
``Span.end`` with an explicit ``parent=`` instead; they never sit on the
stack.

Thread-safety: spans may start on one thread (client ``submit``) and end
on another (the scheduler loop); the tracer's open-span accounting is
locked, and emission goes through the multi-writer-safe EventLog.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

#: Reserved field names in a ``trace.span`` event — span attributes may not
#: shadow them (``Span.end`` silently drops offenders rather than corrupt
#: the schema; the exporter and merge tooling key on these).
RESERVED_SPAN_FIELDS = frozenset(
    {"ts", "kind", "trace", "span", "parent", "name", "lane", "t0", "dur_s"}
)

_TRACEPARENT_VERSION = "00"


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class SpanContext:
    """The serializable identity of one span: ``(trace_id, span_id)``.

    ``trace_id`` is 16 bytes (32 hex chars) shared by every span of one
    request's tree; ``span_id`` is 8 bytes (16 hex chars) unique per span.
    The wire form is the W3C traceparent header: ``00-<trace>-<span>-01``.
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    @classmethod
    def new(cls) -> "SpanContext":
        return cls(_hex_id(16), _hex_id(8))

    def child(self) -> "SpanContext":
        """A fresh span id under the same trace."""
        return SpanContext(self.trace_id, _hex_id(8))

    def to_traceparent(self) -> str:
        return f"{_TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header) -> "SpanContext | None":
        """Parse a traceparent header; None (never an exception) on any
        malformation — an invalid incoming header must degrade to "start a
        new trace", not error the request carrying it (W3C semantics)."""
        if not isinstance(header, str):
            return None
        parts = header.strip().lower().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, _flags = parts
        if len(version) != 2 or version == "ff":
            return None
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return cls(trace_id, span_id)


class Span:
    """One timed operation. Created by the tracer, closed exactly once by
    ``end()`` — which is when (and only when) its event is emitted."""

    __slots__ = (
        "name", "ctx", "parent_id", "lane", "attrs",
        "_t0_wall", "_t0_mono", "_tracer", "_ended",
    )

    def __init__(self, tracer: "Tracer", name: str, ctx: SpanContext,
                 parent_id: "str | None", lane: "str | None", attrs: dict):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.lane = lane
        self.attrs = attrs
        self._t0_wall = time.time()
        self._t0_mono = time.perf_counter()
        self._tracer = tracer
        self._ended = False

    def set(self, **attrs) -> None:
        """Attach/overwrite attributes before the span closes (recorded in
        the close event). Reserved schema fields are refused at end()."""
        self.attrs.update(attrs)

    def end(self, **attrs) -> None:
        """Close the span and emit its ``trace.span`` event. Exactly-once:
        a second end() is counted (``tracer.stats['double_end']``) and
        otherwise ignored — telemetry must never raise into serving code,
        and the span-tree tests read the counter."""
        if attrs:
            self.attrs.update(attrs)
        self._tracer._end_span(self)


class Tracer:
    """Span factory bound to an emit callable (``EventLog.emit`` or
    ``Telemetry.emit`` — anything with the ``(kind, **fields)`` shape)."""

    def __init__(self, emit) -> None:
        self._emit = emit
        self._lock = threading.Lock()
        self._open: dict[str, str] = {}  # span_id -> name (introspection)
        self._local = threading.local()
        self.stats = {"started": 0, "ended": 0, "double_end": 0,
                      "dropped_attrs": 0}

    # ---- introspection (the span-tree completeness surface) ---------------

    @property
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def open_spans(self) -> dict[str, str]:
        """span_id -> name of every not-yet-closed span (a copy)."""
        with self._lock:
            return dict(self._open)

    # ---- span lifecycle ----------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> "Span | None":
        """The innermost ``span()`` context on THIS thread, or None."""
        stack = self._stack()
        return stack[-1] if stack else None

    def start_span(
        self, name: str, parent=None, lane: "str | None" = None, **attrs
    ) -> Span:
        """Open a span. ``parent`` may be a :class:`Span`, a
        :class:`SpanContext` (e.g. parsed from an incoming traceparent), or
        None — None inherits this thread's current ``span()`` context, and
        starts a NEW trace only when there is none."""
        if parent is None:
            parent = self.current()
        if isinstance(parent, Span):
            parent = parent.ctx
        if isinstance(parent, SpanContext):
            ctx, parent_id = parent.child(), parent.span_id
        else:
            ctx, parent_id = SpanContext.new(), None
        span = Span(self, name, ctx, parent_id, lane, attrs)
        with self._lock:
            self.stats["started"] += 1
            self._open[ctx.span_id] = name
        return span

    def _end_span(self, span: Span) -> None:
        if span._ended:
            with self._lock:
                self.stats["double_end"] += 1
            return
        span._ended = True
        dur = time.perf_counter() - span._t0_mono
        with self._lock:
            self.stats["ended"] += 1
            self._open.pop(span.ctx.span_id, None)
        fields = {
            "trace": span.ctx.trace_id,
            "span": span.ctx.span_id,
            "name": span.name,
            "t0": round(span._t0_wall, 6),
            "dur_s": round(dur, 9),
        }
        if span.parent_id is not None:
            fields["parent"] = span.parent_id
        if span.lane is not None:
            fields["lane"] = span.lane
        for key, value in span.attrs.items():
            if key in RESERVED_SPAN_FIELDS or key in fields:
                with self._lock:
                    self.stats["dropped_attrs"] += 1
                continue
            fields[key] = value
        # ts = close time, consistent with every other event kind; t0/dur_s
        # carry the interval (the exporter never trusts ts for geometry).
        self._emit("trace.span", ts=round(span._t0_wall + dur, 6), **fields)

    @contextlib.contextmanager
    def span(self, name: str, parent=None, lane: "str | None" = None, **attrs):
        """Context-manager span: parents to the enclosing ``span()`` on this
        thread (unless ``parent=`` overrides), pushes itself as current for
        the duration, and always closes — even on exception (recorded as
        ``error=<type name>``; the exception propagates untouched)."""
        sp = self.start_span(name, parent=parent, lane=lane, **attrs)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.end(error=type(e).__name__)
            raise
        finally:
            stack.pop()
            if not sp._ended:
                sp.end()


def traced_call(fn, tracer: Tracer, name: str, lane: "str | None" = None,
                **attrs):
    """Wrap ``fn`` so every call runs inside a ``tracer.span(name)`` —
    parenting to whatever span is current on the calling thread. The
    tracing sibling of ``obs.telemetry.timed_call``, with the same
    inertness obligation: when ``fn`` is jitted the span brackets the host
    dispatch, and tracing the wrapper directly must yield a byte-identical
    jaxpr (``telemetry_inert`` contract traces the pool step, slot prefill,
    and verify programs through this exact wrapper)."""

    def wrapped(*args, **kwargs):
        with tracer.span(name, lane=lane, **attrs):
            return fn(*args, **kwargs)

    wrapped.__wrapped__ = fn
    return wrapped


# --------------------------------------------------------------------------
# Perfetto / Chrome trace-event export

#: Fixed lane -> tid mapping: control lanes first, then one lane per serve
#: slot (``slot0``.. at tid 10+), so every export of the same run lays out
#: identically. Unknown lanes allocate past the slots. ``router`` is the
#: front-end dispatcher's own lane (serve/router.py) — in a multi-source
#: merge the router's log is additionally its own PROCESS row, since
#: processes key on the ``source`` tag.
_CONTROL_LANES = {"intake": 1, "scheduler": 2, "train": 3, "router": 4}
_SLOT_TID_BASE = 10


def _lane_tid(lane: str, extra: dict) -> int:
    if lane in _CONTROL_LANES:
        return _CONTROL_LANES[lane]
    if lane.startswith("slot"):
        try:
            return _SLOT_TID_BASE + int(lane[4:])
        except ValueError:
            pass
    if lane not in extra:
        extra[lane] = 1000 + len(extra)
    return extra[lane]


def chrome_trace(events: list) -> dict:
    """``trace.span`` events -> a Chrome trace-event JSON document (the
    ``{"traceEvents": [...]}`` object form), loadable in chrome://tracing
    and ui.perfetto.dev. Each span becomes one complete ("X") event; each
    source file (multi-source merge) becomes one process with its lanes as
    named threads. Non-span events are ignored, so the exporter runs on
    any event log."""
    spans = [
        e for e in events
        if e.get("kind") == "trace.span"
        and isinstance(e.get("t0"), (int, float))
        and isinstance(e.get("dur_s"), (int, float))
    ]
    pids: dict[str, int] = {}
    extra_lanes: dict[tuple, int] = {}
    out: list[dict] = []
    seen_threads: set[tuple] = set()
    base = min((e["t0"] for e in spans), default=0.0)
    for e in spans:
        source = str(e.get("source", "main"))
        if source not in pids:
            pids[source] = len(pids) + 1
            out.append({
                "ph": "M", "name": "process_name", "pid": pids[source],
                "tid": 0, "args": {"name": source},
            })
        pid = pids[source]
        lane = str(e.get("lane", "main"))
        per_source = extra_lanes.setdefault(("extra", source), {})
        tid = _lane_tid(lane, per_source)
        if (pid, tid) not in seen_threads:
            seen_threads.add((pid, tid))
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": lane},
            })
            # Sort index keeps lanes in the fixed tid order in the UI.
            out.append({
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": tid},
            })
        args = {
            k: v for k, v in e.items()
            if k not in ("kind", "t0", "dur_s", "lane", "name", "ts", "source")
        }
        out.append({
            "ph": "X",
            "name": str(e.get("name", "span")),
            "cat": str(e.get("name", "span")).split(".", 1)[0],
            "pid": pid,
            "tid": tid,
            "ts": round((e["t0"] - base) * 1e6, 3),   # microseconds
            "dur": round(max(e["dur_s"], 0.0) * 1e6, 3),
            "args": args,
        })
    out.sort(key=lambda ev: (ev["ph"] != "M", ev.get("ts", 0.0)))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "transformer_tpu.obs trace",
            "sources": sorted(pids),
            "spans": len(spans),
            "base_unix_s": round(base, 6),
        },
    }


def span_tree(events: list) -> dict:
    """Index ``trace.span`` events into ``{trace_id: {span_id: event}}`` —
    the shape the completeness tests and the merge skew estimator walk."""
    trees: dict[str, dict[str, dict]] = {}
    for e in events:
        if e.get("kind") != "trace.span":
            continue
        trace, span = e.get("trace"), e.get("span")
        if isinstance(trace, str) and isinstance(span, str):
            trees.setdefault(trace, {})[span] = e
    return trees
