"""Circuit breaker: the graceful-degradation primitive.

Lives under ``obs/`` (stdlib-only, jax/numpy-free like the rest of the
package) because breaker state is an observability export — gauges and
``serve.breaker`` events — and because the event-log sink itself is one of
the protected subsystems: ``cli/flags.py`` wires a breaker into
``EventLog`` without importing the serve stack. The serving-facing surface
re-exports it from ``transformer_tpu.serve.resilience``, which owns the
rest of the fault-tolerance story (fault plane, error taxonomy,
docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import threading
import time

#: Gauge encoding of breaker state (docs/OBSERVABILITY.md).
BREAKER_STATE_VALUE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Fail a flaky subsystem OPEN to its fallback path, then re-probe.

    closed --K consecutive failures--> open --cooldown--> half_open
    half_open --success--> closed;  half_open --failure--> open (again)

    ``allow()`` is the gate callers consult before using the protected
    subsystem: True while closed (and for the half-open probe once the
    cooldown elapsed), False while open. ``record_failure()`` returns True
    exactly when this call OPENED the breaker (callers warn once per
    outage, not once per fault). ``clock`` is injectable so tests drive
    cooldowns deterministically; transitions reach ``on_transition(name,
    old, new)`` OUTSIDE the internal lock (callbacks may emit telemetry,
    which takes locks of its own).

    Thread-safe: the event-sink breaker is hit by every thread that emits.
    """

    def __init__(
        self,
        name: str,
        threshold: int = 3,
        cooldown_s: float = 5.0,
        clock=time.monotonic,
        on_transition=None,
    ):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.name = name
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0       # consecutive, since the last success
        self._opened_at = 0.0
        self.stats = {"failures": 0, "opens": 0, "closes": 0}

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new: str) -> tuple[str, str]:
        old, self._state = self._state, new
        return old, new

    def _notify(self, moved: tuple[str, str] | None) -> None:
        if moved and self._on_transition is not None:
            self._on_transition(self.name, *moved)

    def allow(self) -> bool:
        moved = None
        with self._lock:
            if self._state == "open":
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                moved = self._transition("half_open")
        self._notify(moved)
        return True

    def record_failure(self) -> bool:
        """Count one fault; True iff this call tripped closed/half_open ->
        open (the "warn once per outage" edge)."""
        moved = None
        with self._lock:
            self.stats["failures"] += 1
            self._failures += 1
            if self._state == "half_open" or (
                self._state == "closed" and self._failures >= self.threshold
            ):
                moved = self._transition("open")
                self._opened_at = self._clock()
                self.stats["opens"] += 1
        self._notify(moved)
        return moved is not None

    def record_success(self) -> None:
        if self._state == "closed" and self._failures == 0:
            return  # steady-state fast path: no lock on the healthy road
        moved = None
        with self._lock:
            if self._state == "open":
                # An OPEN breaker recovers only through its half-open
                # probe: a success from work admitted before the trip
                # (e.g. another slot in the same scheduler step) must not
                # bypass the cooldown — otherwise an intermittent fault
                # flaps the breaker open/closed every step and the
                # degraded-time accounting becomes noise.
                return
            self._failures = 0
            if self._state == "half_open":
                moved = self._transition("closed")
                self.stats["closes"] += 1
        self._notify(moved)
