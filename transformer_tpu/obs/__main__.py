"""``python -m transformer_tpu.obs <summarize|trace|slo|roofline|postmortem>``
— telemetry CLI.

- ``summarize`` aggregates a structured event log (docs/OBSERVABILITY.md
  schema) into the operator-facing numbers: tokens/s, step p50/p95, slot
  utilization, and the per-request latency breakdown (queue → prefill →
  first-token → total). Works on logs from a train run, a serve session, or
  a file that interleaves both (the aggregator keys on ``kind``).
- ``trace`` exports ``trace.span`` events (the ``--trace`` flag's output)
  to Chrome trace-event JSON — load the file in chrome://tracing or
  ui.perfetto.dev; one lane per serve slot plus scheduler/intake/train.
- ``slo`` evaluates declarative SLOs (``obs/slo.py``) as multi-window burn
  rates over the same log.
- ``roofline`` joins an episode's measured per-program dispatch histograms
  (``obs/profile.py``, the ``perf_seconds_*`` stream) against cost-model
  predictions (``--costs`` = an ``analysis costs --format=json`` document)
  and the banked baseline: tokens/s, effective bytes/s, roofline ratio,
  and drift verdicts per program. ``--check`` exits 1 on a banked-band
  breach; ``--update`` re-banks the measured p50s (the pass → perturb →
  fail → ``--update`` → pass workflow the analysis families use).
- ``postmortem`` reconstructs a fleet's last seconds from any mix of
  event logs, ``*.flight.json`` flight-recorder dumps, and the flight
  records the Supervisor embedded in ``route.postmortem`` events.

All three accept MULTIPLE jsonl files (``--merge``): events are tagged with
their source and clock-aligned via per-file skew estimation
(``obs/merge.py``) — the cross-replica aggregation the scale-out roadmap
item requires. ``--since TS`` / ``--last N{s,m,h}`` slice long soak logs.
CPU-only, jax-free — safe to run on a laptop against logs scp'd off TPU
hosts.
"""

from __future__ import annotations

import argparse
import json
import sys

from transformer_tpu.obs.merge import filter_events, merge_events, parse_duration
from transformer_tpu.obs.profile import (
    BASELINE_PATH,
    band_breaches,
    load_baseline,
    measured_from_events,
    predictions_by_program,
    roofline_report,
    write_baseline,
)
from transformer_tpu.obs.quantiles import StreamingHistogram


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    return f"{seconds * 1e3:.1f}ms"


def _span_quantiles(reqs: list[dict], field: str) -> dict | None:
    h = StreamingHistogram()
    for r in reqs:
        v = r.get(field)
        if isinstance(v, (int, float)) and v >= 0:
            h.observe(v)
    return h.snapshot() if h.count else None


def summarize_events(events: list[dict]) -> dict:
    """Event list -> JSON-able report (the text renderer formats this)."""
    report: dict = {"events": len(events)}

    # ---- serve: per-request spans ----------------------------------------
    reqs = [e for e in events if e.get("kind") == "serve.request"]
    if reqs:
        ok = [r for r in reqs if "error" not in r]
        spans = {}
        for field in ("queue_s", "prefill_s", "ttft_s", "total_s"):
            q = _span_quantiles(ok, field)
            if q:
                spans[field] = q
        gen_tokens = sum(int(r.get("new_tokens", 0)) for r in ok)
        busy_s = sum(
            float(r["total_s"]) for r in ok
            if isinstance(r.get("total_s"), (int, float))
        )
        report["serve"] = {
            "requests": len(reqs),
            "errors": len(reqs) - len(ok),
            "generated_tokens": gen_tokens,
            "spans": spans,
            # In-flight tokens/s: generated tokens over summed per-request
            # residency. With N slots busy the wall-clock rate is ~N× this.
            "tokens_per_request_second": (
                round(gen_tokens / busy_s, 2) if busy_s > 0 else None
            ),
        }
        # Speculative decoding: tokens per target-model decode forward
        # (the number speculation exists to raise past 1.0) and draft
        # acceptance. Spans carry "forwards" whenever the scheduler
        # recorded them, so tokens-per-forward is comparable with
        # speculation on OR off.
        forwards = sum(int(r.get("forwards", 0)) for r in ok)
        if forwards:
            report["serve"]["tokens_per_forward"] = round(
                gen_tokens / forwards, 3
            )
        # Prefix cache: prompt tokens restored from stored KV blocks
        # instead of a prefill forward. Spans carry prefix_hit_tokens
        # (zero on misses) only for requests that PARTICIPATED, so the
        # hit rate's denominator excludes opted-out traffic.
        prefix_reqs = [r for r in ok if "prefix_hit_tokens" in r]
        if prefix_reqs:
            hit = sum(int(r["prefix_hit_tokens"]) for r in prefix_reqs)
            prompt = sum(int(r.get("prompt_tokens", 0)) for r in prefix_reqs)
            report["serve"]["prefix_cache"] = {
                "requests": len(prefix_reqs),
                "hit_tokens": hit,
                "prompt_tokens": prompt,
                "hit_rate": round(hit / prompt, 4) if prompt else None,
            }
        drafted = sum(int(r.get("drafted", 0)) for r in ok)
        if drafted:
            accepted = sum(int(r.get("draft_accepted", 0)) for r in ok)
            rate_h = StreamingHistogram()
            for r in ok:
                d = int(r.get("drafted", 0))
                if d > 0:
                    rate_h.observe(int(r.get("draft_accepted", 0)) / d)
            report["serve"]["speculative"] = {
                "drafted": drafted,
                "accepted": accepted,
                "acceptance_rate": round(accepted / drafted, 4),
                # Per-request acceptance-rate spread (p50/p95/... over
                # requests that drafted at least once).
                "request_acceptance": rate_h.snapshot(),
            }

    # ---- serve: circuit breakers (degraded time) -------------------------
    transitions = [e for e in events if e.get("kind") == "serve.breaker"]
    if transitions:
        per_name: dict[str, list[dict]] = {}
        for t in transitions:
            name = t.get("name")
            if isinstance(name, str) and isinstance(t.get("ts"), (int, float)):
                per_name.setdefault(name, []).append(t)
        last_ts = max(
            (e["ts"] for e in events if isinstance(e.get("ts"), (int, float))),
            default=0.0,
        )
        breakers = {}
        for name, ts in sorted(per_name.items()):
            ts.sort(key=lambda t: t["ts"])
            degraded = 0.0
            degraded_since = None
            opens = 0
            for t in ts:
                state = t.get("state")
                if state in ("open", "half_open"):
                    if state == "open":
                        opens += 1
                    if degraded_since is None:
                        degraded_since = t["ts"]
                elif state == "closed" and degraded_since is not None:
                    degraded += t["ts"] - degraded_since
                    degraded_since = None
            if degraded_since is not None:
                # Still degraded at end-of-log: count up to the last event.
                degraded += max(0.0, last_ts - degraded_since)
            breakers[name] = {
                "opens": opens,
                "degraded_s": round(degraded, 6),
                "final_state": ts[-1].get("state"),
            }
        if breakers:
            report.setdefault("serve", {})["breakers"] = breakers

    # ---- router: multi-replica dispatch / failover ------------------------
    dispatches = [e for e in events if e.get("kind") == "route.dispatch"]
    failovers = [e for e in events if e.get("kind") == "route.failover"]
    if dispatches or failovers:
        per_replica: dict[str, int] = {}
        redispatches = 0
        for d in dispatches:
            name = str(d.get("replica"))
            if int(d.get("redispatch", 0) or 0) > 0:
                redispatches += 1
                continue  # request share counts FIRST dispatches only
            if d.get("stage") == "prefill":
                continue  # disaggregated stage 1: the request's share is
                #           attributed to the replica that DECODES it
            per_replica[name] = per_replica.get(name, 0) + 1
        total = sum(per_replica.values())
        report["router"] = {
            "dispatches": len(dispatches),
            "requests": total,
            "redispatches": redispatches,
            "failovers": len(failovers),
            "failed_over_requests": sum(
                len(f.get("orders", ())) for f in failovers
            ),
            "replicas": {
                name: {
                    "requests": n,
                    "share": round(n / total, 4) if total else None,
                }
                for name, n in sorted(per_replica.items())
            },
        }

    # ---- fleet: supervision / autoscaling / router HA ---------------------
    spawns = [e for e in events if e.get("kind") == "route.spawn"]
    retires = [e for e in events if e.get("kind") == "route.retire"]
    scales = [e for e in events if e.get("kind") == "route.scale"]
    takeovers = [e for e in events if e.get("kind") == "route.takeover"]
    if spawns or retires or scales or takeovers:
        heals = [
            e["heal_s"] for e in spawns
            if isinstance(e.get("heal_s"), (int, float))
        ]
        fleet: dict = {
            "respawns": sum(
                1 for e in spawns
                if not e.get("gave_up") and not e.get("scale_up")
            ),
            "gave_up": sum(1 for e in spawns if e.get("gave_up")),
            "warmed_tokens": sum(
                int(e.get("warmed_tokens", 0) or 0) for e in spawns
            ),
            "scale_ups": sum(
                1 for e in scales if e.get("direction") == "up"
            ),
            "scale_downs": sum(
                1 for e in scales if e.get("direction") == "down"
            ),
            "retired": len(retires),
            "takeovers": len(takeovers),
        }
        if heals:
            fleet["time_to_heal_s"] = {
                "count": len(heals),
                "mean": round(sum(heals) / len(heals), 6),
                "max": round(max(heals), 6),
            }
        if scales:
            last = scales[-1]
            fleet["final_fleet_size"] = last.get("fleet_size")
            fleet["last_scale_evidence"] = last.get("evidence")
        if takeovers:
            t = takeovers[-1]
            fleet["takeover"] = {
                k: t.get(k)
                for k in (
                    "epoch", "adopted", "failed", "recovered_answers",
                    "reowned_inflight", "redispatched", "delivered_upto",
                )
                if t.get(k) is not None
            }
        report["fleet"] = fleet

    # ---- upgrade: live-weights rollouts (serve/upgrade.py) ----------------
    upgrades = [e for e in events if e.get("kind") == "route.upgrade"]
    canaries = [e for e in events if e.get("kind") == "route.canary"]
    if upgrades or canaries:
        completed = [e for e in upgrades if e.get("phase") == "completed"]
        rollbacks = [e for e in upgrades if e.get("rolled_back")]
        per_version: dict[str, int] = {}
        for d in dispatches:
            if int(d.get("redispatch", 0) or 0) > 0:
                continue
            if d.get("stage") == "prefill":
                continue
            wv = d.get("weight_version")
            if wv is not None:
                per_version[str(wv)] = per_version.get(str(wv), 0) + 1
        total_v = sum(per_version.values())
        up: dict = {
            "started": sum(1 for e in upgrades if e.get("phase") == "started"),
            "completed": len(completed),
            "rejected": sum(
                1 for e in upgrades if e.get("phase") == "rejected"
            ),
            "rollbacks": len(rollbacks),
            "replicas_swapped": sum(
                1 for e in upgrades if e.get("phase") == "swapped"
            ),
            "per_version_requests": {
                v: {
                    "requests": n,
                    "share": round(n / total_v, 4) if total_v else None,
                }
                for v, n in sorted(per_version.items())
            },
        }
        if completed:
            up["time_to_upgrade_s"] = completed[-1].get("time_to_upgrade_s")
            up["version"] = completed[-1].get("version")
        if rollbacks:
            up["rollback"] = {
                k: rollbacks[-1].get(k)
                for k in ("version", "reason", "evidence")
                if rollbacks[-1].get(k) is not None
            }
        promoted = [c for c in canaries if c.get("phase") == "promoted"]
        started_c = [c for c in canaries if c.get("phase") == "started"]
        if started_c:
            up["canary"] = {
                "replica": started_c[-1].get("replica"),
                "every": started_c[-1].get("every"),
                "window_s": started_c[-1].get("window_s"),
                "promoted": bool(promoted),
                "requests": (
                    promoted[-1].get("requests") if promoted else None
                ),
            }
        report["upgrade"] = up

    # ---- serve: grouped-path batches --------------------------------------
    batches = [e for e in events if e.get("kind") == "serve.batch"]
    if batches:
        h = StreamingHistogram()
        for b in batches:
            v = b.get("batch_s")
            if isinstance(v, (int, float)) and v >= 0:
                h.observe(v)
        report["serve_grouped"] = {
            "batches": len(batches),
            "requests": sum(int(b.get("size", 0)) for b in batches),
            "errors": sum(int(b.get("errors", 0)) for b in batches),
            "batch_s": h.snapshot() if h.count else None,
        }

    # ---- serve: slot utilization from metric snapshots -------------------
    snaps = [e for e in events if e.get("kind") == "metrics.snapshot"]
    if snaps:
        # A crash-truncated final line never parses (read_events skips it),
        # but a snapshot written by a DIFFERENT/older producer can carry a
        # non-dict metrics payload — tolerate, never raise (the summarize
        # CLI must work on exactly the logs crashes leave behind).
        snaps = [s for s in snaps if isinstance(s.get("metrics"), dict)]
    if snaps:
        utils = []
        for s in snaps:
            m = s.get("metrics", {})
            active, total = m.get("serve_slots_active"), m.get("serve_slots_total")
            if isinstance(active, (int, float)) and total:
                utils.append(active / total)
        if utils:
            report.setdefault("serve", {})["slot_utilization"] = {
                "mean": round(sum(utils) / len(utils), 4),
                "max": round(max(utils), 4),
                "samples": len(utils),
            }
        last = snaps[-1].get("metrics", {})
        step_hist = last.get("serve_step_seconds")
        if isinstance(step_hist, dict) and step_hist.get("count"):
            report.setdefault("serve", {})["step_seconds"] = step_hist
        # Paged KV pool utilization (--kv_layout paged): block occupancy
        # over the run from the used/free gauges, plus the aliased-vs-
        # host-restored split of the prefix hit tokens (aliased hits paid
        # ZERO host<->device copies).
        pool_utils = []
        for s in snaps:
            m = s.get("metrics", {})
            used, free = (
                m.get("serve_kv_pool_used_blocks"),
                m.get("serve_kv_pool_free_blocks"),
            )
            if isinstance(used, (int, float)) and isinstance(
                free, (int, float)
            ) and used + free > 0:
                pool_utils.append(used / (used + free))
        if pool_utils:
            kv_pool = {
                "used_blocks": last.get("serve_kv_pool_used_blocks"),
                "free_blocks": last.get("serve_kv_pool_free_blocks"),
                "utilization_mean": round(
                    sum(pool_utils) / len(pool_utils), 4
                ),
                "utilization_max": round(max(pool_utils), 4),
                "samples": len(pool_utils),
            }
            alias = last.get("serve_prefix_alias_tokens_total")
            hit = last.get("serve_prefix_hit_tokens_total")
            if isinstance(alias, (int, float)) and isinstance(
                hit, (int, float)
            ):
                kv_pool["alias_tokens"] = int(alias)
                kv_pool["host_restored_tokens"] = int(hit - alias)
                if hit:
                    kv_pool["alias_rate"] = round(alias / hit, 4)
            report.setdefault("serve", {})["kv_pool"] = kv_pool

    # ---- train: throughput + step-time quantiles -------------------------
    windows = [e for e in events if e.get("kind") == "train.window"]
    if windows:
        steps = sum(int(w.get("steps", 0)) for w in windows)
        tokens = sum(int(w.get("tokens", 0)) for w in windows)
        wall = sum(float(w.get("window_s", 0.0)) for w in windows)
        h = StreamingHistogram()
        for w in windows:
            n = int(w.get("steps", 0))
            ws = float(w.get("window_s", 0.0))
            if n > 0 and ws > 0:
                # A window's wall time, attributed evenly to its steps —
                # the same accounting StepTimer.sync() uses.
                h.observe(ws / n, n=n)
        last = windows[-1]
        report["train"] = {
            "windows": len(windows),
            "steps": steps,
            "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1) if wall > 0 else None,
            "steps_per_sec": round(steps / wall, 2) if wall > 0 else None,
            "step_seconds": h.snapshot() if h.count else None,
            "final": {
                k: last[k]
                for k in ("loss", "accuracy", "grad_norm", "step")
                if k in last
            },
        }
        compiles = [e for e in events if e.get("kind") == "train.compile"]
        if compiles:
            report["train"]["compiles"] = compiles[-1].get("cache_sizes")

    # ---- train: measured memory vs the cost model's prediction -----------
    # The trainer records device.memory_stats() samples (train.memory) and,
    # when the jaxpr cost model could price its step, one train.predicted
    # event. Either side may be absent (older logs, un-traceable configs,
    # backends without allocator stats) — report what exists, never raise.
    mem = [e for e in events if e.get("kind") == "train.memory"]
    if mem:
        report.setdefault("train", {})["memory"] = mem[-1].get(
            "devices", mem[-1].get("stats")
        )
    predicted = [e for e in events if e.get("kind") == "train.predicted"]
    if predicted:
        p = predicted[-1]
        entry = {
            k: p[k]
            for k in ("peak_bytes", "flops", "bytes_moved", "tokens_per_step")
            if isinstance(p.get(k), (int, float))
        }
        measured = None
        for e in mem:
            devices = e.get("devices")
            if not isinstance(devices, dict):
                continue
            for stats in devices.values():
                if isinstance(stats, dict) and isinstance(
                    stats.get("peak_bytes_in_use"), (int, float)
                ):
                    peak = stats["peak_bytes_in_use"]
                    measured = peak if measured is None else max(measured, peak)
        if measured is not None:
            entry["measured_peak_bytes"] = measured
            if entry.get("peak_bytes"):
                # > 1: the allocator holds more than the model predicts
                # (fragmentation, workspace, other programs); << 1 or >> 1
                # drift over rounds is the regression signal.
                entry["measured_over_predicted"] = round(
                    measured / entry["peak_bytes"], 3
                )
        if entry:
            report.setdefault("train", {})["predicted"] = entry

    # ---- perf: measured programs vs the cost model (obs/profile.py) ------
    # The profiler's per-program histograms ride metrics.snapshot; join
    # them against the banked baseline's frozen predictions. Tolerant when
    # either side is absent: no profiler stream -> no section; an unbanked
    # program rows without the bytes/drift columns. `obs roofline` is the
    # full report (this section skips the --costs join).
    perf = roofline_report(events)
    if perf.get("programs"):
        report["perf"] = perf

    # ---- bench attribution ----------------------------------------------
    bench = [e for e in events if str(e.get("kind", "")).startswith("bench.")]
    if bench:
        counts: dict[str, int] = {}
        for e in bench:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        report["bench"] = counts

    # ---- tracing (span volume only; `obs trace` renders the timeline) ----
    spans = [e for e in events if e.get("kind") == "trace.span"]
    if spans:
        traces = {e.get("trace") for e in spans}
        report["tracing"] = {"spans": len(spans), "traces": len(traces)}

    # ---- SLO breach transitions ------------------------------------------
    burns = [e for e in events if e.get("kind") == "slo.burn"]
    if burns:
        slo: dict[str, dict] = {}
        for e in burns:
            name = str(e.get("name"))
            entry = slo.setdefault(name, {"breaches": 0})
            if e.get("breached"):
                entry["breaches"] += 1
            entry["final_breached"] = bool(e.get("breached"))
        report["slo_transitions"] = slo

    return report


def render_text(report: dict) -> str:
    lines = [f"{report['events']} events"]
    serve = report.get("serve")
    if serve:
        # A serve section can exist with only snapshot-derived fields (a
        # session scraped before any request finished) — .get throughout.
        lines.append(
            f"serve: {serve.get('requests', 0)} requests "
            f"({serve.get('errors', 0)} errored), "
            f"{serve.get('generated_tokens', 0)} tokens generated"
        )
        util = serve.get("slot_utilization")
        if util:
            lines.append(
                f"  slot utilization: mean {util['mean'] * 100:.1f}%, "
                f"max {util['max'] * 100:.1f}% over {util['samples']} samples"
            )
        if serve.get("tokens_per_request_second"):
            lines.append(
                f"  decode rate: {serve['tokens_per_request_second']} "
                "tokens/s per in-flight request"
            )
        if serve.get("tokens_per_forward"):
            lines.append(
                f"  tokens/forward: {serve['tokens_per_forward']}"
            )
        pc = serve.get("prefix_cache")
        if pc:
            rate = (
                f" ({pc['hit_rate'] * 100:.1f}% hit rate)"
                if pc.get("hit_rate") is not None else ""
            )
            lines.append(
                f"  prefix cache: {pc['hit_tokens']}/{pc['prompt_tokens']} "
                f"prompt tokens reused{rate} over {pc['requests']} requests"
            )
        kv = serve.get("kv_pool")
        if kv:
            lines.append(
                f"  kv pool: {kv.get('used_blocks')} used / "
                f"{kv.get('free_blocks')} free blocks, utilization mean "
                f"{kv['utilization_mean'] * 100:.1f}% max "
                f"{kv['utilization_max'] * 100:.1f}% over "
                f"{kv['samples']} samples"
            )
            if kv.get("alias_tokens") is not None:
                rate = (
                    f" ({kv['alias_rate'] * 100:.1f}% aliased)"
                    if kv.get("alias_rate") is not None else ""
                )
                lines.append(
                    f"  prefix restore split: {kv['alias_tokens']} tokens "
                    f"device-aliased (zero copies) vs "
                    f"{kv['host_restored_tokens']} host-restored{rate}"
                )
        spec = serve.get("speculative")
        if spec:
            q = spec.get("request_acceptance") or {}
            spread = (
                f" (per-request p50 {q['p50'] * 100:.0f}%)" if q else ""
            )
            lines.append(
                f"  speculative: {spec['accepted']}/{spec['drafted']} drafts "
                f"accepted ({spec['acceptance_rate'] * 100:.1f}%){spread}"
            )
        for field, label in (
            ("queue_s", "queue"), ("prefill_s", "prefill"),
            ("ttft_s", "first token"), ("total_s", "total"),
        ):
            q = serve.get("spans", {}).get(field)
            if q:
                lines.append(
                    f"  {label:>11}: p50 {_fmt_s(q['p50'])}  "
                    f"p95 {_fmt_s(q['p95'])}  p99 {_fmt_s(q['p99'])}  "
                    f"max {_fmt_s(q['max'])}"
                )
        step = serve.get("step_seconds")
        if step:
            lines.append(
                f"  scheduler step: p50 {_fmt_s(step['p50'])}  "
                f"p95 {_fmt_s(step['p95'])} over {step['count']} steps"
            )
        brk = serve.get("breakers")
        if brk:
            parts = [
                f"{name} {b['opens']} open(s), "
                f"{_fmt_s(b['degraded_s'])} degraded"
                + ("" if b.get("final_state") == "closed"
                   else f" [{b.get('final_state')}]")
                for name, b in sorted(brk.items())
            ]
            lines.append("  breakers: " + "; ".join(parts))
    router = report.get("router")
    if router:
        line = (
            f"router: {router['requests']} requests over "
            f"{len(router['replicas'])} replica(s)"
        )
        if router.get("failovers"):
            line += (
                f"; {router['failovers']} failover(s), "
                f"{router['failed_over_requests']} request(s) failed over, "
                f"{router['redispatches']} redispatched"
            )
        lines.append(line)
        for name, rep in sorted(router["replicas"].items()):
            share = (
                f" ({rep['share'] * 100:.1f}%)"
                if rep.get("share") is not None else ""
            )
            lines.append(f"  {name}: {rep['requests']} requests{share}")
    fleet = report.get("fleet")
    if fleet:
        parts = []
        if fleet.get("respawns"):
            h = fleet.get("time_to_heal_s")
            heal = (
                f" (time-to-heal mean {_fmt_s(h['mean'])}, "
                f"max {_fmt_s(h['max'])})" if h else ""
            )
            parts.append(f"{fleet['respawns']} respawn(s){heal}")
        if fleet.get("warmed_tokens"):
            parts.append(f"{fleet['warmed_tokens']} cache tokens warmed")
        if fleet.get("gave_up"):
            parts.append(f"{fleet['gave_up']} crash-loop give-up(s)")
        if fleet.get("scale_ups") or fleet.get("scale_downs"):
            part = (
                f"scaled up x{fleet['scale_ups']}, "
                f"down x{fleet['scale_downs']}"
            )
            if fleet.get("final_fleet_size") is not None:
                part += f" (final fleet {fleet['final_fleet_size']})"
            parts.append(part)
        if fleet.get("retired"):
            parts.append(f"{fleet['retired']} retired")
        if fleet.get("takeovers"):
            t = fleet.get("takeover", {})
            part = f"{fleet['takeovers']} router takeover(s)"
            if t:
                part += (
                    f" [epoch {t.get('epoch')}: "
                    f"{t.get('recovered_answers', 0)} recovered, "
                    f"{t.get('reowned_inflight', 0)} re-owned, "
                    f"{t.get('redispatched', 0)} re-dispatched]"
                )
            parts.append(part)
        lines.append("fleet: " + "; ".join(parts))
    upgrade = report.get("upgrade")
    if upgrade:
        parts = []
        if upgrade.get("completed"):
            part = f"{upgrade['completed']} rollout(s) completed"
            if upgrade.get("time_to_upgrade_s") is not None:
                part += (
                    f" (last {_fmt_s(upgrade['time_to_upgrade_s'])} "
                    f"to version {upgrade.get('version')})"
                )
            parts.append(part)
        elif upgrade.get("started"):
            parts.append(f"{upgrade['started']} rollout(s) started")
        if upgrade.get("rollbacks"):
            rb = upgrade.get("rollback", {})
            part = f"{upgrade['rollbacks']} rolled back"
            if rb.get("reason"):
                part += f" ({rb['reason']})"
            parts.append(part)
        if upgrade.get("rejected"):
            parts.append(f"{upgrade['rejected']} rejected at verification")
        canary = upgrade.get("canary")
        if canary:
            verdict = "promoted" if canary.get("promoted") else "pending"
            parts.append(
                f"canary {canary.get('replica')} every "
                f"{canary.get('every')}th order, {verdict}"
            )
        lines.append("upgrade: " + "; ".join(parts))
        for v, rep in upgrade.get("per_version_requests", {}).items():
            share = (
                f" ({rep['share'] * 100:.1f}%)"
                if rep.get("share") is not None else ""
            )
            lines.append(f"  version {v}: {rep['requests']} requests{share}")
    grouped = report.get("serve_grouped")
    if grouped:
        line = (
            f"serve (grouped): {grouped['requests']} requests "
            f"({grouped['errors']} errored) in {grouped['batches']} batches"
        )
        if grouped.get("batch_s"):
            q = grouped["batch_s"]
            line += f"; batch p50 {_fmt_s(q['p50'])}  p95 {_fmt_s(q['p95'])}"
        lines.append(line)
    train = report.get("train")
    if train:
        tps = train.get("tokens_per_sec")
        lines.append(
            f"train: {train.get('steps', 0)} steps, "
            f"{train.get('tokens', 0)} tokens"
            + (f", {tps:,.0f} tokens/s" if tps else "")
        )
        step = train.get("step_seconds")
        if step:
            lines.append(
                f"  step time: p50 {_fmt_s(step['p50'])}  "
                f"p95 {_fmt_s(step['p95'])}  p99 {_fmt_s(step['p99'])}"
            )
        final = train.get("final", {})
        if final:
            parts = [f"{k} {final[k]:.4f}" if isinstance(final[k], float)
                     else f"{k} {final[k]}" for k in sorted(final)]
            lines.append("  final: " + ", ".join(parts))
        if train.get("compiles"):
            total = sum(train["compiles"].values())
            lines.append(f"  jit programs compiled: {total} {train['compiles']}")
        if train.get("memory"):
            lines.append(f"  device memory: {train['memory']}")
        pred = train.get("predicted")
        if pred:
            line = f"  cost model: predicted peak {pred.get('peak_bytes', '?')}B/step"
            if pred.get("measured_peak_bytes") is not None:
                line += f", measured peak {pred['measured_peak_bytes']}B"
            if pred.get("measured_over_predicted") is not None:
                line += f" (measured/predicted {pred['measured_over_predicted']}x)"
            lines.append(line)
    perf = report.get("perf")
    if perf:
        lines.append(
            f"perf: {len(perf['programs'])} measured program(s) "
            "(`obs roofline` renders the full join)"
        )
        for r in perf["programs"]:
            line = (
                f"  {r['program']}: p50 {r['p50_ms']:.3f}ms "
                f"over {r['dispatches']} dispatches"
            )
            if r.get("measured_tokens_per_s"):
                line += f", {r['measured_tokens_per_s']} tokens/s"
            if r.get("roofline_ratio") is not None:
                line += f", roofline {r['roofline_ratio']}"
            if r.get("drift") is not None:
                line += f", drift {r['drift']}x" + (
                    "" if r.get("in_band", True) else " OUT OF BAND"
                )
            lines.append(line)
    bench = report.get("bench")
    if bench:
        lines.append(
            "bench: " + ", ".join(f"{k.split('.', 1)[1]} x{v}"
                                  for k, v in sorted(bench.items()))
        )
    tracing = report.get("tracing")
    if tracing:
        lines.append(
            f"tracing: {tracing['spans']} spans across {tracing['traces']} "
            "traces (`obs trace` exports the timeline)"
        )
    slo = report.get("slo_transitions")
    if slo:
        parts = [
            f"{name} {s['breaches']} breach(es)"
            + (" [still breached]" if s.get("final_breached") else "")
            for name, s in sorted(slo.items())
        ]
        lines.append("slo: " + "; ".join(parts))
    sources = report.get("sources")
    if sources:
        parts = [
            f"{name} ({s['events']} events"
            + (f", skew {s['skew_s']:+g}s" if s.get("skew_s") else "")
            + ")"
            for name, s in sorted(sources.items())
        ]
        lines.append("sources: " + "; ".join(parts))
    if len(lines) == 1:
        lines.append("no serve/train/bench telemetry kinds found")
    return "\n".join(lines)


def render_roofline_text(report: dict) -> str:
    rows = report.get("programs", [])
    lines = [
        f"{len(rows)} measured program(s); roofline peak "
        f"{report.get('peak_bytes_per_s', 0):.4g} B/s"
    ]
    for r in rows:
        line = (
            f"  {r['program']}: p50 {r['p50_ms']:.3f}ms "
            f"p95 {r['p95_ms']:.3f}ms over {r['dispatches']} dispatches"
        )
        if r.get("measured_tokens_per_s"):
            line += f", {r['measured_tokens_per_s']} tokens/s"
        if r.get("predicted_bytes_moved"):
            line += (
                f"; predicted {r['predicted_bytes_moved']}B moved -> "
                f"{r['effective_bytes_per_s']:.4g} B/s effective, "
                f"roofline {r['roofline_ratio']}"
            )
        if r.get("measured_over_predicted_tokens") is not None:
            line += (
                f"; measured/predicted tokens/s "
                f"{r['measured_over_predicted_tokens']}x"
            )
        if r.get("drift") is not None:
            verdict = "in band" if r.get("in_band") else "OUT OF BAND"
            line += f"; drift {r['drift']}x {r.get('band')} {verdict}"
        lines.append(line)
    if len(lines) == 1:
        lines.append(
            "no perf_seconds_* histograms found (profiler not armed, or "
            "no metrics.snapshot flushed?)"
        )
    return "\n".join(lines)


def _flight_doc(path: str) -> dict | None:
    """json.load the whole file: a flight dump is ONE dict carrying an
    ``events`` ring and no top-level ``kind`` — anything else (a JSONL
    log, a torn file) is not a dump and falls back to the merge path."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if (
        isinstance(doc, dict)
        and isinstance(doc.get("events"), list)
        and "kind" not in doc
    ):
        return doc
    return None


def postmortem_report(
    events: list[dict], flights: list[dict], info: dict | None = None
) -> dict:
    """Fuse merged event logs, standalone flight dumps, and the records
    embedded in ``route.postmortem`` events into one fleet timeline plus
    a per-victim postmortem table (their final ``serve.request`` spans
    are the rows an incident review reads first)."""
    timeline = [dict(e) for e in events]
    postmortems: list[dict] = []

    def ingest(record: dict, replica: str, origin: str) -> None:
        ring_events = [
            e for e in (record.get("events") or []) if isinstance(e, dict)
        ]
        ring_spans = [
            s for s in (record.get("spans") or []) if isinstance(s, dict)
        ]
        for entry in ring_events + ring_spans:
            tagged = dict(entry)
            tagged["source"] = f"postmortem:{replica}"
            timeline.append(tagged)
        reqs = [e for e in ring_events if e.get("kind") == "serve.request"]
        postmortems.append({
            "replica": replica,
            "origin": origin,
            "reason": record.get("reason"),
            "ts": record.get("ts"),
            "pid": record.get("pid"),
            "events": len(ring_events),
            "spans": len(ring_spans),
            "final_requests": reqs[-5:],
        })

    for e in events:
        if e.get("kind") == "route.postmortem" and isinstance(
            e.get("record"), dict
        ):
            ingest(e["record"], str(e.get("replica")), str(e.get("origin")))
    for doc in flights:
        ingest(doc, str(doc.get("source") or doc.get("pid") or "?"), "file")

    timeline = [t for t in timeline if isinstance(t.get("ts"), (int, float))]
    timeline.sort(key=lambda t: t["ts"])
    report = {
        "events": len(events),
        "flight_files": len(flights),
        "postmortems": postmortems,
        "timeline": timeline[-80:],
    }
    if info:
        report.update(info)
    return report


def render_postmortem_text(report: dict) -> str:
    pms = report.get("postmortems", [])
    lines = [
        f"{len(pms)} postmortem(s) over {report.get('events', 0)} log "
        f"event(s) + {report.get('flight_files', 0)} flight dump file(s)"
    ]
    for p in pms:
        lines.append(
            f"  {p['replica']} [{p['origin']}] reason={p.get('reason')} "
            f"pid={p.get('pid')}: {p['events']} events, {p['spans']} spans, "
            f"{len(p['final_requests'])} final request(s)"
        )
        for r in p["final_requests"]:
            total = r.get("total_s")
            lines.append(
                f"    request order={r.get('order')} "
                f"tokens={r.get('new_tokens')}"
                + (f" total={_fmt_s(total)}"
                   if isinstance(total, (int, float)) else "")
                + (" ERROR" if "error" in r else "")
            )
    tail = report.get("timeline", [])[-15:]
    if tail:
        lines.append("last seconds:")
        for t in tail:
            src = t.get("source")
            lines.append(
                f"  {t['ts']:.3f} "
                + (f"[{src}] " if src else "")
                + str(t.get("kind"))
            )
    sources = report.get("sources")
    if sources:
        parts = [
            f"{name} ({s['events']} events"
            + (f", skew {s['skew_s']:+g}s" if s.get("skew_s") else "")
            + ")"
            for name, s in sorted(sources.items())
        ]
        lines.append("sources: " + "; ".join(parts))
    return "\n".join(lines)


def _add_common_args(p) -> None:
    p.add_argument(
        "jsonl", nargs="+",
        help="event log(s) written via --metrics_jsonl; pass several to "
        "aggregate across processes/replicas",
    )
    p.add_argument(
        "--merge", action="store_true",
        help="treat inputs as a multi-source merge (implied when more than "
        "one file is given): tag events with their source, align clocks "
        "via per-file skew estimation, and report the per-source table "
        "(with one file, forces the source-tagged report)",
    )
    p.add_argument(
        "--no-align", action="store_true",
        help="merge without clock-skew alignment (raw timestamps)",
    )
    p.add_argument(
        "--since", type=float, default=None, metavar="TS",
        help="drop events before this unix timestamp (seconds)",
    )
    p.add_argument(
        "--last", type=str, default=None, metavar="N{s,m,h}",
        help="keep only the trailing window of the log, e.g. 90s / 5m / 2h "
        "(measured back from the newest event)",
    )


def _load(args) -> "tuple[list, dict]":
    """Common input path: read one file or merge several, then apply the
    time-window slice. Returns (events, merge_report)."""
    events, info = merge_events(args.jsonl, align=not args.no_align)
    if args.last is not None:
        events = filter_events(events, last=parse_duration(args.last))
    if args.since is not None:
        events = filter_events(events, since=args.since)
    # The per-source table rides along whenever this IS a merge — more
    # than one input, or --merge forcing the tagged report for one file.
    return events, info if (len(args.jsonl) > 1 or args.merge) else {}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m transformer_tpu.obs",
        description="telemetry tools (docs/OBSERVABILITY.md)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser(
        "summarize", help="render a run report from JSONL event log(s)"
    )
    _add_common_args(p_sum)
    p_sum.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is diff-able across runs)",
    )
    p_trace = sub.add_parser(
        "trace",
        help="export trace.span events to Chrome trace-event JSON "
        "(chrome://tracing / ui.perfetto.dev)",
    )
    _add_common_args(p_trace)
    p_trace.add_argument(
        "--out", default="trace.json",
        help="output path for the trace-event JSON (default: trace.json)",
    )
    p_slo = sub.add_parser(
        "slo", help="evaluate SLO burn rates over the event log(s)"
    )
    _add_common_args(p_slo)
    p_slo.add_argument(
        "--slo_spec", default="",
        help="SLO spec string (obs/slo.py grammar, same as the serve "
        "flag); '' = the default objectives",
    )
    p_slo.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    p_roof = sub.add_parser(
        "roofline",
        help="measured-vs-predicted per-program report from the profiler "
        "stream (perf_seconds_* histograms in metrics.snapshot)",
    )
    _add_common_args(p_roof)
    p_roof.add_argument(
        "--costs", default=None, metavar="JSON",
        help="`analysis costs --format=json` document to join predictions "
        "from (without it, the banked baseline's frozen predictions apply)",
    )
    p_roof.add_argument(
        "--baseline", default=BASELINE_PATH,
        help="banked roofline baseline (default: the checked-in "
        "obs/roofline_baseline.json)",
    )
    p_roof.add_argument(
        "--update", action="store_true",
        help="re-bank the episode's measured p50s into --baseline "
        "(absolute times are per-host: run on the box that enforces "
        "the band)",
    )
    p_roof.add_argument(
        "--check", action="store_true",
        help="exit 1 when any banked program's measured p50 left its band",
    )
    p_roof.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    p_pm = sub.add_parser(
        "postmortem",
        help="reconstruct the fleet's last seconds from event logs, "
        "*.flight.json dumps, and route.postmortem records",
    )
    _add_common_args(p_pm)
    p_pm.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    args = parser.parse_args(argv)

    if args.cmd == "postmortem":
        # Inputs are a MIX of flight dumps (whole-file JSON) and JSONL
        # logs — sniff each before the merge machinery sees it.
        flights, jsonls = [], []
        for path in args.jsonl:
            doc = _flight_doc(path)
            if doc is not None:
                doc.setdefault("source", path)
                flights.append(doc)
            else:
                jsonls.append(path)
        events, info = [], {}
        if jsonls:
            try:
                events, info = merge_events(jsonls, align=not args.no_align)
            except OSError as e:
                print(f"cannot read {', '.join(jsonls)}: {e}", file=sys.stderr)
                return 2
            if args.last is not None:
                events = filter_events(events, last=parse_duration(args.last))
            if args.since is not None:
                events = filter_events(events, since=args.since)
        report = postmortem_report(
            events, flights,
            info if (len(jsonls) > 1 or args.merge) else {},
        )
        if args.format == "json":
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print(render_postmortem_text(report))
        return 0

    try:
        events, info = _load(args)
    except OSError as e:
        print(f"cannot read {', '.join(args.jsonl)}: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"bad arguments: {e}", file=sys.stderr)
        return 2

    if args.cmd == "summarize":
        report = summarize_events(events)
        report.update(info)
        if args.format == "json":
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print(render_text(report))
        return 0

    if args.cmd == "roofline":
        costs_doc = None
        if args.costs:
            try:
                with open(args.costs, encoding="utf-8") as f:
                    costs_doc = json.load(f)
            except (OSError, ValueError) as e:
                print(f"cannot read --costs {args.costs}: {e}", file=sys.stderr)
                return 2
        if args.update:
            measured = measured_from_events(events)
            if not measured:
                print(
                    "no perf_seconds_* histograms in the episode; "
                    "nothing to bank",
                    file=sys.stderr,
                )
                return 2
            prior = load_baseline(args.baseline)
            # Predictions to freeze next to the banked p50s: a --costs
            # document when given, else whatever the prior bank froze.
            preds = (
                predictions_by_program(costs_doc)
                if costs_doc else dict(prior.get("programs") or {})
            )
            doc = write_baseline(
                args.baseline, measured, predictions=preds,
                peak_bytes_per_s=prior.get("peak_bytes_per_s"),
            )
            print(
                f"banked {len(doc['programs'])} program(s) -> {args.baseline}"
            )
            return 0
        report = roofline_report(
            events, costs=costs_doc, baseline=load_baseline(args.baseline)
        )
        report.update(info)
        if args.format == "json":
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print(render_roofline_text(report))
        if args.check:
            breaches = band_breaches(report)
            if breaches:
                for r in breaches:
                    print(
                        f"BAND BREACH {r['program']}: drift {r['drift']}x "
                        f"outside {r['band']}",
                        file=sys.stderr,
                    )
                return 1
        return 0

    if args.cmd == "trace":
        from transformer_tpu.obs.trace import chrome_trace

        doc = chrome_trace(events)
        if info.get("sources"):
            doc["otherData"]["skews"] = {
                name: s["skew_s"] for name, s in info["sources"].items()
            }
        with open(args.out, "w") as f:
            json.dump(doc, f)
        n = doc["otherData"]["spans"]
        if not n:
            print(
                f"warning: no trace.span events found (run with --trace?); "
                f"wrote an empty trace to {args.out}",
                file=sys.stderr,
            )
        else:
            print(
                f"{n} spans from {len(doc['otherData']['sources'])} "
                f"source(s) -> {args.out} (load in chrome://tracing or "
                "ui.perfetto.dev)"
            )
        return 0

    # slo
    from transformer_tpu.obs.slo import (
        DEFAULT_SLOS,
        evaluate_slos,
        parse_slo_spec,
        render_slo_text,
    )

    try:
        specs = parse_slo_spec(args.slo_spec) if args.slo_spec else DEFAULT_SLOS
    except ValueError as e:
        print(f"bad --slo_spec: {e}", file=sys.stderr)
        return 2
    report = evaluate_slos(events, specs)
    if info:
        report.update(info)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_slo_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
