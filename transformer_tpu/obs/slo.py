"""Declarative SLOs evaluated as multi-window burn rates.

A raw latency histogram answers "what is p95 right now"; an SLO answers
"are we spending our error budget faster than we can afford". This module
(stdlib-only, jax-free, like the rest of obs) defines the spec shape, the
burn-rate math, and two consumers of it:

- **Offline**: :func:`evaluate_slos` over any event log (or a multi-source
  merge) — ``python -m transformer_tpu.obs slo <jsonl>`` renders the
  report, sliceable with ``--since`` / ``--last``.
- **Live**: :class:`SLOEngine`, fed one ``serve.request`` span dict at a
  time by the scheduler at the answer boundaries it already owns, exporting
  ``serve_slo_burn_<name>`` gauges and emitting a ``slo.burn`` event at
  every breach-state TRANSITION (never per evaluation — a breached soak
  must not flood its own event log).

Burn rate, per window: ``bad_fraction / (1 - objective)`` — 1.0 means
"exactly consuming the error budget", N means the budget is gone in
``window / N``. A spec BREACHES when every configured window burns > 1
simultaneously (the multi-window rule from the SRE workbook: the long
window proves it matters, the short window proves it is still happening).

The four spec kinds map onto what the serving tier records
(docs/OBSERVABILITY.md carries the reference table):

==================  =====================================================
``availability``    bad = the request answered with an error
``ttft_p95``        bad = ``ttft_s`` above ``threshold_s`` (objective
                    0.95 = the p95 target; generalizes to any quantile)
``deadline_miss``   bad = the answer's taxonomy code is ``deadline``
``acceptance_rate`` weighted: bad = rejected draft tokens, total =
                    drafted (objective = the acceptance-rate floor)
==================  =====================================================
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

SLO_KINDS = ("availability", "ttft_p95", "deadline_miss", "acceptance_rate")

#: Default multi-window pair (seconds): fast "is it still happening" and
#: slow "does it matter" — override per spec with ``windows=60+300``.
DEFAULT_WINDOWS = (300.0, 3600.0)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One objective. ``objective`` is the good-fraction target (0.99 =
    "99% of requests succeed"; for ``acceptance_rate`` it is the floor);
    ``threshold_s`` parameterizes the latency kinds."""

    name: str
    kind: str
    objective: float
    threshold_s: float = 0.0
    windows: tuple = DEFAULT_WINDOWS

    def __post_init__(self):
        if self.kind not in SLO_KINDS:
            raise ValueError(
                f"unknown SLO kind {self.kind!r}; valid: {', '.join(SLO_KINDS)}"
            )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective} "
                f"({self.name})"
            )
        if self.kind == "ttft_p95" and self.threshold_s <= 0:
            raise ValueError(
                f"{self.name}: ttft_p95 needs threshold=<seconds> > 0"
            )
        if not self.windows or any(w <= 0 for w in self.windows):
            raise ValueError(f"{self.name}: windows must be positive")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


#: The serve tier's default objectives — deliberately loose (CI boxes and
#: laptops must not page themselves); production overrides via --slo_spec.
DEFAULT_SLOS = (
    SLOSpec("availability", "availability", 0.99),
    SLOSpec("ttft_p95", "ttft_p95", 0.95, threshold_s=2.0),
    SLOSpec("deadline_miss", "deadline_miss", 0.99),
    SLOSpec("acceptance_rate", "acceptance_rate", 0.5),
)


def parse_slo_spec(spec: str) -> "tuple[SLOSpec, ...]":
    """``--slo_spec`` grammar (mirrors ``--fault_spec``):

        spec   := clause (';' clause)*
        clause := kind [':' param (',' param)*]
        param  := 'objective=' float | 'threshold=' seconds
                | 'windows=' seconds('+' seconds)* | 'name=' str

    Example — 99.9% availability with tight windows, 500ms TTFT p95::

        availability:objective=0.999,windows=60+600;ttft_p95:threshold=0.5

    ``none`` (or ``off``) disables SLO evaluation entirely.
    """
    spec = spec.strip()
    if spec.lower() in ("none", "off"):
        return ()
    out = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, params = clause.partition(":")
        kw: dict = {"kind": kind.strip(), "name": kind.strip()}
        for param in params.split(",") if params else []:
            key, sep, value = param.partition("=")
            key, value = key.strip(), value.strip()
            if not sep:
                raise ValueError(f"slo_spec param {param!r} is not key=value")
            if key == "objective":
                kw["objective"] = float(value)
            elif key == "threshold":
                kw["threshold_s"] = float(value)
            elif key == "windows":
                kw["windows"] = tuple(float(v) for v in value.split("+"))
            elif key == "name":
                kw["name"] = value
            else:
                raise ValueError(
                    f"unknown slo_spec key {key!r} (valid: objective, "
                    "threshold, windows, name)"
                )
        if "objective" not in kw:
            defaults = {s.kind: s for s in DEFAULT_SLOS}
            if kw["kind"] in defaults:
                kw.setdefault("objective", defaults[kw["kind"]].objective)
                if "threshold_s" not in kw:
                    kw["threshold_s"] = defaults[kw["kind"]].threshold_s
            else:
                raise ValueError(f"unknown SLO kind {kw['kind']!r}")
        out.append(SLOSpec(**kw))
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO names in spec: {names}")
    return tuple(out)


def span_sample(spec: SLOSpec, span: dict) -> "tuple[float, float] | None":
    """One ``serve.request`` span dict -> ``(bad_weight, total_weight)``
    for this spec, or None when the span does not participate (e.g. a
    request that never drafted contributes nothing to the acceptance
    floor). The ONE place event fields map onto SLO arithmetic — the live
    engine and the offline report both call it."""
    if spec.kind == "availability":
        return (1.0 if "error" in span else 0.0), 1.0
    if spec.kind == "deadline_miss":
        return (1.0 if span.get("code") == "deadline" else 0.0), 1.0
    if spec.kind == "ttft_p95":
        ttft = span.get("ttft_s")
        if not isinstance(ttft, (int, float)):
            # Errored/tokenless requests have no first token; they are
            # availability's problem, not the latency SLO's.
            return None
        return (1.0 if ttft > spec.threshold_s else 0.0), 1.0
    if spec.kind == "acceptance_rate":
        drafted = span.get("drafted")
        if not isinstance(drafted, (int, float)) or drafted <= 0:
            return None
        accepted = span.get("draft_accepted", 0)
        accepted = accepted if isinstance(accepted, (int, float)) else 0
        return float(drafted - accepted), float(drafted)
    return None


def _window_burn(
    samples, now: float, spec: SLOSpec
) -> dict:
    """Burn rates over ``spec.windows`` for TIME-ORDERED (ts, bad, total)
    samples, in ONE newest-to-oldest pass: the live engine calls this
    between decode steps, so cost must be O(samples), never
    O(windows x samples) — each cutoff is crossed exactly once on the
    walk, and the walk stops at the oldest window's edge."""
    order = sorted(set(spec.windows))          # ascending window size =
    cutoffs = [now - w for w in order]         # descending cutoff time
    sums: dict = {}
    bad = total = 0.0
    i = 0
    for ts, b, t in reversed(samples):
        while i < len(order) and ts < cutoffs[i]:
            sums[order[i]] = (bad, total)
            i += 1
        if i >= len(order):
            break  # older than every window: nothing left to count
        bad += b
        total += t
    while i < len(order):
        sums[order[i]] = (bad, total)
        i += 1
    windows = {}
    for w in spec.windows:
        b, t = sums[w]
        frac = (b / t) if t else None
        windows[f"{w:g}s"] = {
            "total": t,
            "bad": b,
            "bad_fraction": None if frac is None else round(frac, 6),
            "burn_rate": (
                None if frac is None else round(frac / spec.budget, 4)
            ),
        }
    return windows


def _breached(windows: dict) -> bool:
    burns = [w["burn_rate"] for w in windows.values()]
    return bool(burns) and all(b is not None and b > 1.0 for b in burns)


def evaluate_slos(
    events: list, specs=DEFAULT_SLOS, now: "float | None" = None
) -> dict:
    """Offline SLO report over an event log: for each spec, per-window
    totals / bad fraction / burn rate, plus the multi-window breach
    verdict. ``now`` defaults to the newest event timestamp (end of log),
    so reports over historical logs stay meaningful."""
    spans = [e for e in events if e.get("kind") == "serve.request"]
    if now is None:
        now = max(
            (e["ts"] for e in events if isinstance(e.get("ts"), (int, float))),
            default=time.time(),
        )
    report: dict = {"now": round(now, 6), "requests": len(spans), "slos": {}}
    for spec in specs:
        samples = []
        for span in spans:
            s = span_sample(spec, span)
            if s is not None and isinstance(span.get("ts"), (int, float)):
                samples.append((span["ts"], s[0], s[1]))
        # _window_burn's one-pass walk needs time order; offline logs can
        # interleave sources (merge) or clock steps, so sort here (the
        # live engine's deque is ordered by construction).
        samples.sort(key=lambda s: s[0])
        windows = _window_burn(samples, now, spec)
        report["slos"][spec.name] = {
            "kind": spec.kind,
            "objective": spec.objective,
            **(
                {"threshold_s": spec.threshold_s}
                if spec.kind == "ttft_p95" else {}
            ),
            "windows": windows,
            "breached": _breached(windows),
        }
    return report


class SLOEngine:
    """Streaming burn-rate evaluation for the serving loop.

    ``record(span)`` is called wherever a ``serve.request`` event is
    emitted (host-side answer boundaries); ``maybe_evaluate()``
    re-computes burn rates at most once per ``interval`` seconds, sets
    the ``serve_slo_burn_<name>`` gauges (the max across that spec's
    windows — the paging number), and emits one ``slo.burn`` event per
    breach-state transition. THREAD-SAFE: most answers come from the
    scheduler loop, but backpressure refusals and pre-answered responses
    record from CLIENT threads (``submit``/``submit_done``), so one lock
    serializes sample appends against evaluation's iteration/pruning
    (evaluation itself stays scheduler-loop-only). Near-simultaneous
    cross-thread appends can land microseconds out of order; the
    one-pass window walk tolerates that at a window edge (one sample
    attributed one window over), which is noise at burn-rate scale.
    Memory is bounded: samples older than the longest window are pruned
    on every evaluation."""

    def __init__(
        self,
        specs=DEFAULT_SLOS,
        registry=None,
        emit=None,
        interval: float = 5.0,
        clock=time.time,
    ):
        self.specs = tuple(specs)
        self._registry = registry
        self._emit = emit
        self._interval = max(float(interval), 0.0)
        self._clock = clock
        self._samples = {s.name: deque() for s in self.specs}
        self._breached = {s.name: False for s in self.specs}
        self._last_eval: "float | None" = None
        self._lock = threading.Lock()
        self._gauges = {}
        if registry is not None:
            for s in self.specs:
                self._gauges[s.name] = registry.gauge(
                    f"serve_slo_burn_{s.name}",
                    f"max burn rate across {s.kind} windows "
                    "(1.0 = consuming the error budget exactly)",
                )

    def record(self, span: dict, ts: "float | None" = None) -> None:
        ts = ts if ts is not None else self._clock()
        with self._lock:
            for spec in self.specs:
                s = span_sample(spec, span)
                if s is not None:
                    self._samples[spec.name].append((ts, s[0], s[1]))

    def maybe_evaluate(self, force: bool = False) -> "dict | None":
        now = self._clock()
        if (
            not force
            and self._last_eval is not None
            and now - self._last_eval < self._interval
        ):
            return None
        self._last_eval = now
        return self.evaluate(now)

    def evaluate(self, now: "float | None" = None) -> dict:
        now = now if now is not None else self._clock()
        out = {}
        for spec in self.specs:
            with self._lock:
                # Prune + snapshot under the lock (client threads append
                # concurrently; iterating a mutating deque raises); the
                # burn math and gauge/event work run on the copy.
                samples = self._samples[spec.name]
                horizon = now - max(spec.windows)
                while samples and samples[0][0] < horizon:
                    samples.popleft()
                samples = list(samples)
            windows = _window_burn(samples, now, spec)
            burns = [
                w["burn_rate"] for w in windows.values()
                if w["burn_rate"] is not None
            ]
            max_burn = max(burns) if burns else 0.0
            if spec.name in self._gauges:
                self._gauges[spec.name].set(max_burn)
            breached = _breached(windows)
            if breached != self._breached[spec.name]:
                self._breached[spec.name] = breached
                if self._emit is not None:
                    # "spec" (not "kind") for the SLO kind: the emit
                    # callable's first positional IS the event kind.
                    self._emit(
                        "slo.burn",
                        name=spec.name,
                        spec=spec.kind,
                        objective=spec.objective,
                        breached=breached,
                        burn_rate=max_burn,
                        windows={
                            k: w["burn_rate"] for k, w in windows.items()
                        },
                    )
            out[spec.name] = {
                "windows": windows, "breached": breached,
                "burn_rate": max_burn,
            }
        return out


def render_slo_text(report: dict) -> str:
    lines = [
        f"{report['requests']} requests, "
        f"{len(report['slos'])} SLO(s) @ now={report['now']}"
    ]
    for name, slo in report["slos"].items():
        head = f"{name} ({slo['kind']}, objective {slo['objective']:g}"
        if "threshold_s" in slo:
            head += f", threshold {slo['threshold_s']:g}s"
        head += "): " + ("BREACHED" if slo["breached"] else "ok")
        lines.append(head)
        for wname, w in slo["windows"].items():
            if w["burn_rate"] is None:
                lines.append(f"  {wname:>8}: no samples")
            else:
                lines.append(
                    f"  {wname:>8}: burn {w['burn_rate']:g}x "
                    f"({w['bad']:g}/{w['total']:g} bad)"
                )
    return "\n".join(lines)
