"""Multi-source telemetry: merge JSONL event logs across processes.

The ROADMAP's horizontal scale-out item is gated on telemetry that
aggregates across replicas — ``obs summarize`` / ``obs trace`` / ``obs
slo`` over the union of N per-process logs. This module is that prerequisite
(stdlib-only, like the rest of obs):

- **Source tagging**: every merged event gains a ``source`` field (the
  file's basename, disambiguated when two paths share one), so a report or
  Perfetto export can always say which replica produced what.
- **Clock alignment**: wall clocks on different hosts disagree. When trace
  context crossed the process boundary (``obs/trace.py`` traceparent — a
  router span whose child span landed in a replica's log), every cross-file
  parent/child span pair constrains the files' relative skew: the child's
  interval, shifted by the true skew, must nest inside its parent's. The
  estimator intersects those constraints per file pair (midpoint of the
  feasible interval, median over pairs) and shifts each file onto the first
  file's clock. Files with no cross-file trace lineage keep their own clock
  (skew 0 — nothing to align against, and guessing would be worse than
  honesty: the per-source skew table in the report says which happened).
- **Time-window slicing**: ``--since TS`` / ``--last N{s,m,h}`` filtering
  (applied AFTER alignment, so one cutoff means one instant across
  replicas) — long soak logs become sliceable without external tooling.
"""

from __future__ import annotations

import os
import statistics

from transformer_tpu.obs.events import read_events

#: ``--last`` suffix -> seconds.
_DURATION_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(text: str) -> float:
    """``'90s' / '5m' / '2h'`` (bare numbers = seconds) -> seconds.
    Raises ValueError on malformation — CLI flags must fail loudly."""
    text = str(text).strip()
    if not text:
        raise ValueError("empty duration")
    unit = 1.0
    if text[-1].lower() in _DURATION_UNITS:
        unit = _DURATION_UNITS[text[-1].lower()]
        text = text[:-1]
    value = float(text)  # ValueError propagates with the original text
    if value < 0:
        raise ValueError(f"duration must be >= 0, got {value}")
    return value * unit


def filter_events(
    events: list, since: "float | None" = None, last: "float | None" = None
) -> list:
    """Keep events with ``ts >= cutoff``. ``since`` is an absolute unix
    timestamp; ``last`` is seconds counted back from the newest event in
    the list (the end of the log, NOT the current clock — a report over an
    old log must not come back empty). Both given: the later cutoff wins.
    Events without a numeric ``ts`` are dropped by any filter."""
    if since is None and last is None:
        return events
    cutoff = since if since is not None else float("-inf")
    if last is not None:
        end = max(
            (e["ts"] for e in events if isinstance(e.get("ts"), (int, float))),
            default=0.0,
        )
        cutoff = max(cutoff, end - last)
    return [
        e for e in events
        if isinstance(e.get("ts"), (int, float)) and e["ts"] >= cutoff
    ]


def _unique_names(paths: list) -> list:
    """Basenames, disambiguated with the parent directory (then an index)
    when two paths collide — the ``source`` tags must be distinct or the
    per-source accounting silently merges replicas."""
    names = [os.path.basename(p) or p for p in paths]
    out = []
    for i, (path, name) in enumerate(zip(paths, names)):
        if names.count(name) > 1:
            parent = os.path.basename(os.path.dirname(os.path.abspath(path)))
            name = f"{parent}/{name}" if parent else f"{name}#{i}"
        while name in out:
            name = f"{name}#{i}"
        out.append(name)
    return out


def _span_index(events: list) -> dict:
    """span_id -> (t0, t1, parent_id|None) over this file's trace.span
    events."""
    idx = {}
    for e in events:
        if e.get("kind") != "trace.span":
            continue
        t0, dur = e.get("t0"), e.get("dur_s")
        span = e.get("span")
        if not (isinstance(t0, (int, float)) and isinstance(dur, (int, float))
                and isinstance(span, str)):
            continue
        idx[span] = (float(t0), float(t0) + float(dur), e.get("parent"))
    return idx


def estimate_skews(per_file_events: list) -> list:
    """Per-file clock offset (seconds to SUBTRACT from every timestamp)
    relative to file 0's clock, from cross-file parent/child span pairs.

    For a child recorded in file B at ``[c0, c1]`` under a parent recorded
    in file A at ``[p0, p1]``, the true child interval ``[c0 - s, c1 - s]``
    must nest in the parent's: feasible ``s`` in ``[c1 - p1, c0 - p0]``.
    One pair's point estimate is the interval midpoint — symmetric slack,
    the same assumption NTP makes about path delay — and a file pair's
    estimate is the median over its pairs (robust to one weird span).
    Estimates chain: a file aligned only against file 2 inherits file 2's
    offset. Unconstrained files get 0.0.
    """
    n = len(per_file_events)
    indexes = [_span_index(evs) for evs in per_file_events]
    # pairwise[(a, b)] = list of point estimates for (file b's clock minus
    # file a's clock).
    pairwise: dict[tuple, list] = {}
    for b, idx_b in enumerate(indexes):
        for span_id, (c0, c1, parent) in idx_b.items():
            if not isinstance(parent, str):
                continue
            for a, idx_a in enumerate(indexes):
                if a == b or parent not in idx_a:
                    continue
                p0, p1, _ = idx_a[parent]
                lo, hi = c1 - p1, c0 - p0
                pairwise.setdefault((a, b), []).append((lo + hi) / 2.0)
    offsets: list = [None] * n
    offsets[0] = 0.0
    # Propagate along constraint edges breadth-first from file 0 (then from
    # any still-unanchored file, which becomes its own island's reference).
    for root in range(n):
        if offsets[root] is None:
            offsets[root] = 0.0
        frontier = [root]
        while frontier:
            a = frontier.pop()
            for (x, y), ests in pairwise.items():
                if x == a and offsets[y] is None:
                    offsets[y] = offsets[a] + statistics.median(ests)
                    frontier.append(y)
                elif y == a and offsets[x] is None:
                    offsets[x] = offsets[a] - statistics.median(ests)
                    frontier.append(x)
    return [round(o, 6) for o in offsets]


def merge_events(
    paths: list, align: bool = True
) -> "tuple[list, dict]":
    """Read N JSONL logs into one time-sorted event list. Every event is
    tagged with its ``source`` (existing tags from an earlier merge pass
    are preserved); with ``align`` (default), per-file clock skew is
    estimated from cross-file trace lineage and subtracted from ``ts`` and
    span ``t0`` so one timeline is coherent across replicas.

    Returns ``(events, report)`` where ``report['sources']`` maps each
    source tag to its event count and applied ``skew_s`` — summarize
    surfaces it so an operator can see what alignment did."""
    names = _unique_names(paths)
    per_file = [read_events(p) for p in paths]
    skews = estimate_skews(per_file) if align and len(paths) > 1 else [0.0] * len(paths)
    merged: list = []
    sources: dict[str, dict] = {}
    for name, events, skew in zip(names, per_file, skews):
        for e in events:
            e.setdefault("source", name)
            if skew:
                if isinstance(e.get("ts"), (int, float)):
                    e["ts"] = round(e["ts"] - skew, 6)
                if e.get("kind") == "trace.span" and isinstance(
                    e.get("t0"), (int, float)
                ):
                    e["t0"] = round(e["t0"] - skew, 6)
            merged.append(e)
        sources[name] = {"events": len(events), "skew_s": skew}
    merged.sort(
        key=lambda e: e["ts"] if isinstance(e.get("ts"), (int, float)) else 0.0
    )
    return merged, {"sources": sources}
