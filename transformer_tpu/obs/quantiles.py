"""Online quantile estimation: one fixed-size log-bucketed histogram.

The single quantile implementation in the repo — ``utils/profiling.StepTimer``
and the obs :class:`~transformer_tpu.obs.registry.Histogram` both wrap this
class rather than keeping their own percentile code. Design constraints:

- **Dependency-free** (stdlib ``math`` only): the obs package must be
  importable from anywhere — ``bench.py``'s wrapper process, the summarize
  CLI, test helpers — without paying a jax/numpy import.
- **O(1) memory, O(1) observe**: geometric buckets over ``[lo, hi)`` with a
  fixed growth factor; a serving process recording one sample per decode
  step must never grow state with traffic.
- **Bounded relative error**: a quantile is reported as the geometric
  midpoint of its bucket, so the error is at most ``sqrt(growth) - 1``
  (~3.9% at the default 1.08 growth) — plenty for p50/p95/p99 latency
  reporting, and the same shape Prometheus client libraries use.

Values below ``lo`` clamp into the first bucket, values at or above ``hi``
into the last — AND are counted (``underflow`` / ``overflow``, surfaced by
``snapshot()``), so a mis-ranged histogram announces itself instead of
silently reporting clamped tails as real quantiles. Exact
``min``/``max``/``sum``/``count`` are tracked on the side so summaries stay
honest at the tails.
"""

from __future__ import annotations

import math


class StreamingHistogram:
    """Fixed log-bucketed online histogram with approximate quantiles.

    The default range [1e-6, 1e4) in seconds spans microsecond host ops to
    hours-long windows — wide enough for every duration this repo records.
    """

    __slots__ = (
        "lo", "hi", "growth", "_log_lo", "_log_growth", "_counts",
        "count", "total", "sum_squares", "min", "max",
        "underflow", "overflow",
    )

    def __init__(
        self, lo: float = 1e-6, hi: float = 1e4, growth: float = 1.08
    ) -> None:
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError(
                f"need 0 < lo < hi and growth > 1, got lo={lo} hi={hi} "
                f"growth={growth}"
            )
        self.lo, self.hi, self.growth = lo, hi, growth
        self._log_lo = math.log(lo)
        self._log_growth = math.log(growth)
        n = int(math.ceil((math.log(hi) - self._log_lo) / self._log_growth))
        self._counts = [0] * max(n, 1)
        self.count = 0
        self.total = 0.0
        self.sum_squares = 0.0
        self.min = math.inf
        self.max = -math.inf
        # Samples outside [lo, hi): clamped into the edge buckets (above),
        # but COUNTED — a nonzero tally means the configured range is wrong
        # for this stream and the reported tail quantiles are clamp
        # artifacts, not measurements.
        self.underflow = 0
        self.overflow = 0

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``value`` ``n`` times (``n > 1`` attributes one measured
        window to the identical samples inside it — the StepTimer pattern,
        where a window's wall time is known but per-step times are not)."""
        if n < 1:
            return
        value = float(value)
        if value != value:  # NaN: poison nothing, record nothing
            return
        self.count += n
        self.total += value * n
        self.sum_squares += value * value * n
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value < self.lo:
            self.underflow += n
        elif value >= self.hi:
            self.overflow += n
        self._counts[self._index(value)] += n

    def _index(self, value: float) -> int:
        if value < self.lo:
            return 0
        i = int((math.log(value) - self._log_lo) / self._log_growth)
        return min(i, len(self._counts) - 1)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (q in [0, 1]); 0.0 when empty."""
        if not self.count:
            return 0.0
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        # Rank of the wanted sample (1-based), walked over bucket counts.
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                # Geometric midpoint of bucket i, clamped to observed range.
                mid = self.lo * self.growth ** (i + 0.5)
                return min(max(mid, self.min), self.max)
        return self.max  # unreachable; counts always sum to self.count

    def percentiles(self, ps=(50, 95, 99)) -> dict[str, float]:
        return {f"p{p:g}": self.quantile(p / 100.0) for p in ps}

    def buckets(self) -> list[tuple[float, int]]:
        """(upper_bound, count) for every NON-EMPTY bucket, ascending — the
        export shape the Prometheus and tfevents sinks consume."""
        out = []
        for i, c in enumerate(self._counts):
            if c:
                out.append((self.lo * self.growth ** (i + 1), c))
        return out

    def snapshot(self) -> dict:
        """JSON-able summary (the form the event log and summarize CLI use)."""
        if not self.count:
            return {"count": 0}
        out = {
            "count": self.count,
            "sum": self.total,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            **self.percentiles(),
        }
        # Only when nonzero: the common in-range case stays schema-stable
        # for every existing snapshot consumer, and a present key IS the
        # warning.
        if self.underflow:
            out["underflow"] = self.underflow
        if self.overflow:
            out["overflow"] = self.overflow
        return out
