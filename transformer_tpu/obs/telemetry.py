"""The telemetry bundle the CLIs wire through train/serve.

One :class:`Telemetry` object carries the whole obs surface: a
:class:`~transformer_tpu.obs.registry.MetricsRegistry`, an optional
:class:`~transformer_tpu.obs.events.EventLog`, and the periodic sinks —
a Prometheus text file rewritten atomically every ``interval`` seconds and
a ``metrics.snapshot`` event appended to the log on the same cadence.
``cli/flags.py flags_to_telemetry`` builds it from ``--metrics_jsonl`` /
``--metrics_port`` / ``--metrics_interval``; passing ``telemetry=None``
everywhere keeps the zero-overhead default.

Design rule (contract-checked by ``analysis/contracts.py telemetry_inert``):
nothing in this module imports jax or touches device values. Recording
happens at existing host sync points; :func:`timed_call` wraps a jitted
callable without adding a single operation to its trace.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from transformer_tpu.obs.events import EventLog
from transformer_tpu.obs.registry import Histogram, MetricsRegistry


def timed_call(
    fn: Callable, histogram: Histogram | None = None, counter=None
) -> Callable:
    """Wrap ``fn`` so each call's host wall time lands in ``histogram`` (and
    ``counter`` counts calls). Under async dispatch this measures dispatch
    latency, not device time — the StepTimer's synced windows remain the
    throughput source of truth; this catches host-side stalls.

    Jaxpr-inert by construction: the wrapper runs OUTSIDE any trace when
    ``fn`` is a jitted callable, and when traced directly (the contract
    check) it forwards ``fn``'s outputs untouched — ``make_jaxpr`` of the
    wrapped and unwrapped function must be byte-identical.
    """

    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if histogram is not None:
            histogram.observe(time.perf_counter() - t0)
        if counter is not None:
            counter.inc()
        return out

    wrapped.__wrapped__ = fn
    return wrapped


class Telemetry:
    """Registry + event log + periodic sinks, as one pass-around handle.

    ``trace=True`` additionally carries a :class:`~transformer_tpu.obs.
    trace.Tracer` bound to this bundle's event emit — the scheduler and
    trainer consult ``telemetry.tracer`` and record hierarchical
    ``trace.span`` events when it is set (docs/OBSERVABILITY.md tracing
    section). Off by default: spans multiply event volume per request, so
    tracing is an explicit opt-in (``--trace``), while staying answer- and
    jaxpr-inert whenever it IS on (contract-checked).
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        events: EventLog | None = None,
        prom_path: str | None = None,
        interval: float = 10.0,
        trace: bool = False,
    ) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self.events = events
        self.prom_path = prom_path
        self.interval = max(float(interval), 0.0)
        # -inf, not 0.0: perf_counter's epoch is unspecified (host boot on
        # Linux), so "now - 0 < interval" would skip the first flush on any
        # machine whose uptime is shorter than the interval — the first
        # flush must ALWAYS run.
        self._last_flush = float("-inf")
        self._server = None
        self._t0 = time.time()
        self.tracer = None
        # Armed on demand (arm_profiler / arm_flight): the per-program
        # dispatch profiler (obs/profile.py) and the always-on flight
        # recorder (obs/flight.py). None keeps both surfaces free.
        self.profiler = None
        self.flight = None
        if trace:
            from transformer_tpu.obs.trace import Tracer

            self.tracer = Tracer(self.emit)

    # ---- optional subsystems ---------------------------------------------

    def arm_profiler(self, baseline: dict | None = None):
        """Attach a :class:`~transformer_tpu.obs.profile.ProgramProfiler`
        bound to this bundle's registry and emit (perf_* metrics ride the
        snapshot/prom sinks; perf.drift events ride the log)."""
        from transformer_tpu.obs.profile import ProgramProfiler

        self.profiler = ProgramProfiler(
            registry=self.registry, emit=self.emit, baseline=baseline
        )
        return self.profiler

    def arm_flight(
        self, path: str | None, capacity: int = 256, autodump_s: float = 2.0
    ):
        """Attach a :class:`~transformer_tpu.obs.flight.FlightRecorder`
        tapped off :meth:`emit`; ``maybe_flush`` drives its autodumps and
        ``close`` writes the final record."""
        from transformer_tpu.obs.flight import FlightRecorder

        self.flight = FlightRecorder(
            path, capacity=capacity, autodump_s=autodump_s,
            registry=self.registry, emit=self.emit,
        )
        return self.flight

    # ---- events -----------------------------------------------------------

    def emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)
        if self.flight is not None:
            self.flight.record(kind, fields)

    # ---- periodic sinks ---------------------------------------------------

    def maybe_flush(self, force: bool = False) -> bool:
        """Run the periodic sinks if ``interval`` has elapsed (or ``force``).
        Cheap to call every scheduler step / train dispatch: the common case
        is one ``perf_counter`` read and a compare."""
        now = time.perf_counter()
        # The flight recorder's autodump runs at ITS cadence (autodump_s),
        # not the sink interval — a SIGKILL can't trigger a dump, so the
        # on-disk record's staleness bound must not inherit the (much
        # longer) snapshot interval.
        if self.flight is not None:
            self.flight.maybe_dump()
        if not force and now - self._last_flush < self.interval:
            return False
        self._last_flush = now
        self.emit("metrics.snapshot", metrics=self.registry.snapshot())
        if self.prom_path:
            self._write_prom()
        if self.events is not None:
            self.events.flush()
        return True

    def _write_prom(self) -> None:
        """Atomic rewrite (tmp + rename): a scraper tailing the file never
        sees a torn exposition."""
        import sys

        tmp = f"{self.prom_path}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(self.registry.to_prometheus_text())
            os.replace(tmp, self.prom_path)
        except OSError as e:
            # Same downgrade contract as EventLog: one stderr warning, then
            # the sink goes quiet — the observed process never dies (and a
            # scraper sees a stale-but-valid file, not a torn one).
            print(
                f"obs: prometheus file {self.prom_path} unwritable ({e}); "
                "sink disabled for this process",
                file=sys.stderr,
            )
            self.prom_path = None

    def close(self) -> None:
        self.maybe_flush(force=True)
        if self.flight is not None:
            self.flight.dump("close")
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self.events is not None:
            self.events.close()

    # ---- health -----------------------------------------------------------

    def health(self) -> dict:
        """Liveness + sink states, the ``/healthz`` document. ``ok`` is
        False only when a sink has hard-downgraded (broken event log) —
        breaker-open is a transient, reported but not fatal."""
        doc: dict = {
            "ok": True,
            "pid": os.getpid(),
            "ts": round(time.time(), 6),
            "uptime_s": round(time.time() - self._t0, 3),
            "sinks": {
                "prom_file": {"enabled": bool(self.prom_path)},
            },
        }
        if self.events is not None:
            ev = {"broken": bool(getattr(self.events, "_broken", False))}
            breaker = getattr(self.events, "_breaker", None)
            if breaker is not None:
                ev["breaker"] = getattr(breaker, "state", "unknown")
            doc["sinks"]["event_log"] = ev
            if ev["broken"]:
                doc["ok"] = False
        if self.flight is not None:
            doc["flight"] = {
                "depth": self.flight.depth(),
                "dumps": self.flight.dumps,
                "broken": self.flight._broken,
            }
        if self.profiler is not None:
            doc["profiler"] = dict(self.profiler.stats)
        return doc

    # ---- scrape endpoint --------------------------------------------------

    def start_prometheus_server(self, port: int) -> int:
        """Serve ``GET /metrics`` (text exposition) and ``GET /healthz``
        (liveness JSON) on ``port`` from a daemon thread; returns the bound
        port (pass 0 to let the OS pick — tests). stdlib ``http.server``
        only: the obs package takes no dependencies."""
        import http.server
        import json as _json

        registry = self.registry
        telemetry = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                if self.path == "/healthz":
                    doc = telemetry.health()
                    body = _json.dumps(doc).encode()
                    self.send_response(200 if doc["ok"] else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = registry.to_prometheus_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        self._server = server
        return server.server_address[1]


def device_memory_stats(device: Any) -> dict | None:
    """Best-effort ``device.memory_stats()`` (PJRT exposes it on TPU/GPU;
    CPU returns None or omits the method). Returns the small stable subset
    worth recording, or None when the backend has nothing."""
    probe = getattr(device, "memory_stats", None)
    if probe is None:
        return None
    try:
        stats = probe()
    except (RuntimeError, NotImplementedError):
        return None
    if not stats:
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in stats:
            out[key] = int(stats[key])
    return out or None
