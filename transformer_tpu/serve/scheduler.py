"""Continuous (in-flight) batching for decoder-only LM serving.

The grouped path in ``cli/serve.py`` decodes each drained batch TO
COMPLETION before any newly queued request gets a slot: one straggler with
a long generation holds an entire batch's worth of chip time hostage, and a
request that arrives one tick after a batch launches waits out the whole
batch. This module replaces that with a step-level scheduler over a fixed
pool of KV-cache slots:

- **Slot pool**: ``num_slots`` independent single-request KV caches stacked
  into one device-resident pytree (leading slot axis). One jitted
  ``_pool_step`` advances EVERY slot one token per call (a vmapped
  ``transformer_decode_step`` — each slot carries its own cache index, so
  slots sit at unrelated positions in unrelated requests).
- **Admission by prefill-into-slot**: a newly queued request claims a free
  slot mid-flight; its prompt is ingested in one chunked
  ``transformer_prefill`` pass into that slot's cache (the slot's index is
  reset — stale K/V from the previous occupant is provably invisible, the
  position mask zeroes anything at positions the new request has not
  written). Prefill lengths are bucketed (``prefill_len_for``) so serving
  never recompiles per prompt length.
- **Cross-request prefix reuse** (``prefix_cache=``, ``serve/
  prefix_cache.py``): admission first walks a host-side radix trie of
  stored KV blocks for the longest block-aligned prefix an earlier request
  already computed; matched blocks are copied into the slot's cache with
  one jitted ``_slot_restore`` (no model forward) and only the unmatched
  suffix is chunk-prefilled. Retirement slices the slot's prompt-region KV
  back into the trie. Greedy answers are byte-identical cache on/off;
  per-request ``"cache_prefix": false`` opts out of both directions.
- **Retirement at step boundaries**: a slot that emits EOS (or exhausts its
  ``max_new`` budget) is retired and recycled at the next step boundary; the
  remaining slots never wait for it.
- **Speculative decoding** (``speculate_k > 0``, ``serve/speculative.py``):
  each step becomes a verify step — every occupied slot feeds its pending
  token plus up to ``k`` lookahead tokens (un-ingested prompt tail first,
  then drafter proposals) through ONE static-width ``_pool_verify``
  forward; the accepted prefix is kept and the rejected tail is erased by
  O(1) index rollback (``_pool_rollback``). Greedy answers stay
  byte-identical; mixed speculative/non-speculative slots share the one
  compiled program. Refused for rolling-window caches (eviction defeats
  rollback).

Outputs are bit-identical to ``serve_batch=1`` sequential serving (each
request alone through ``train.decode.generate``): the per-slot decode is the
same cached step at the same positions, picks go through the same
``sample_token`` with the same position-keyed rng folding, and masked cache
slots contribute exactly zero to attention regardless of their stale
content. ``tests/test_scheduler.py`` pins this.

Per-request error isolation (the ``cli/serve.py`` grouped-path guarantee)
holds structurally here: requests fail at admission (encode/validation) —
one poisoned request answers with its error and never enters the pool, so
co-batched requests are untouched.

With a ``telemetry=`` handle (``obs.Telemetry``, docs/OBSERVABILITY.md) the
scheduler records per-request spans (enqueue→admit→prefill→first-token→
finish), slot-occupancy/backlog gauges, and admission/retirement/error
counters — all host-side at step boundaries: answers stay byte-identical
and the hot path compiles the same programs (both pinned in tests).

Fault tolerance (``serve/resilience.py``, docs/ROBUSTNESS.md): requests
may carry ``deadline_ms`` (honored at queue/prefill/decode-step
boundaries; expiry frees the slot and answers a structured ``deadline``
error with the partial continuation), ``cancel(order)`` registers a
cancellation from any thread that the scheduler loop executes at the next
step boundary, ``max_backlog`` bounds admission with immediate
``backpressure`` answers, and transient admission faults retry with
jittered exponential backoff before answering ``transient``. Circuit
breakers fail speculation and prefix reuse OPEN to the plain byte-parity
path after K consecutive faults (half-open re-probe after a cooldown),
with state exported as obs gauges + ``serve.breaker`` events — the chaos
suite (tests/test_resilience.py) pins that fault storms lose no request,
slot, or prefix pin, and that greedy answers return byte-identical once
the breakers close, at zero steady-state recompiles.

Tracing (``telemetry.tracer`` set — the ``--trace`` flag): every request
becomes a span tree (``serve.request`` root; ``serve.queue`` /
``serve.admit`` / ``serve.prefill`` / ``serve.decode`` children, plus
``prefix.match`` / ``prefix.restore`` / ``prefix.insert`` and the
step-level ``scheduler.step`` / ``spec.draft`` / ``spec.verify`` /
``spec.rollback`` spans), emitted as ``trace.span`` events on the same
JSONL log and exportable to Perfetto with ``python -m transformer_tpu.obs
trace``. A request dict may carry a W3C ``"traceparent"`` — the root span
parents under it, so a fronting router's trace context propagates across
the process boundary. Error answers, retry/backoff attempts
(``serve.retry`` events) and breaker transitions carry the victim
request's ``trace`` id, so a chaos episode reconstructs as one tree.
Tracing is host-side bookkeeping at the same boundaries as the metrics:
answers stay byte-identical and the compiled programs are jaxpr-identical
tracing on vs. off (``telemetry_inert`` contract + tests/test_trace.py).

SLOs (``slos=`` — specs or a ``--slo_spec`` string, ``obs/slo.py``):
every answer feeds a streaming burn-rate engine; ``serve_slo_burn_*``
gauges and ``slo.burn`` breach-transition events ride the same telemetry,
and ``python -m transformer_tpu.obs slo`` renders the report offline.

Paged KV memory (``kv_layout="paged"``, docs/SERVING.md): every slot is
backed by ONE device-resident block pool per layer through a per-slot
block table (``kernels/kv_pool.py``) instead of a dense ``max_total``
buffer — resident KV proportional to used tokens, prefix-cache hits
restored by block-table ALIASING (zero host copies, zero forwards),
speculative rollback a table truncation, copy-on-write guarding every
write into a shared block. Answers are byte-identical to the dense
layout (the paged step gathers dense-ordered views through the tables
and runs the SAME vmapped forward); pool exhaustion degrades spill →
``transient`` at admission → a structured ``resource`` preemption
mid-flight, never a corrupted neighbor.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import zlib
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from transformer_tpu.config import PAD_ID, ModelConfig
from transformer_tpu.data.seeding import keyed_rng
from transformer_tpu.models.decoder import init_decoder_caches
from transformer_tpu.models.paged_decode import (
    check_paged_flash_config,
    paged_decode_forward,
)
from transformer_tpu.models.transformer import (
    transformer_decode_step,
    transformer_prefill,
    transformer_verify,
)
from transformer_tpu.ops.attention import (
    insert_kv_blocks,
    kv_buffer_keys,
    slice_kv_blocks,
)
from transformer_tpu.serve.resilience import (
    BREAKER_STATE_VALUE,
    CircuitBreaker,
    TransientError,
    backoff_ms,
    classify_error,
    error_answer,
    maybe_fail,
)
from transformer_tpu.serve.speculative import (
    NgramDrafter,
    build_verify_row,
    filtered_probs,
    judge_row,
    sampled_accept,
    verify_row_picks,
)
from transformer_tpu.train.decode import (
    _detokenize_rows,
    prefill_len_for,
    sample_token,
)


def abstract_pool_caches(cfg: ModelConfig, num_slots: int, max_total: int):
    """The slot pool's KV cache pytree as ``ShapeDtypeStruct``s — the ONE
    statement of the pool's device layout (per-slot caches from
    ``init_decoder_caches`` stacked on a leading slot axis), shared by the
    abstract analyses (``analysis/contracts.py`` jaxpr twins,
    ``analysis/costs.py`` memory/FLOP budgets) so they can never drift from
    what the scheduler actually allocates. Nothing is allocated here."""
    per_slot = jax.eval_shape(lambda: init_decoder_caches(cfg, 1, max_total))
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((num_slots, *x.shape), x.dtype), per_slot
    )


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _pool_step(params, pool_caches, toks, cfg: ModelConfig):
    """One decode step for every slot: (N,) tokens -> ((N, V) logits,
    updated pool caches). vmap over the slot axis: each slot runs a batch-1
    ``transformer_decode_step`` at its OWN cache index (free slots step too —
    a fixed-shape program beats per-occupancy recompiles; their writes land
    at masked positions and are overwritten at admission)."""

    def one(tok, caches):
        pos = caches[0]["index"]
        logits, caches = transformer_decode_step(
            params, tok[None, None], None, None, caches, pos, cfg
        )
        return logits[0], caches

    return jax.vmap(one)(toks, pool_caches)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _pool_verify(params, pool_caches, toks, cfg: ModelConfig):
    """One speculative VERIFY step for every slot: (N, W) candidate rows ->
    ((N, W, V) logits — one distribution per fed position — and updated
    pool caches). The W-wide sibling of ``_pool_step``, vmapping
    ``transformer_verify`` (the chunked-prefill S_q > 1 cache-write path)
    over the slot axis. Every slot feeds a full static-W row — occupied
    slots pad short rows with PAD lookahead, free slots feed all-PAD — so
    mixed speculative/non-speculative pools run ONE fixed-shape program.
    Each slot's index advances by W inside; the host decides per-slot
    acceptance and rolls back via ``_pool_rollback``."""

    def one(tok_row, caches):
        pos = caches[0]["index"]
        logits, caches = transformer_verify(
            params, tok_row[None, :], caches, pos, cfg
        )
        return logits[0], caches

    return jax.vmap(one)(toks, pool_caches)


@partial(jax.jit, donate_argnums=(0,))
def _pool_rollback(pool_caches, delta):
    """O(1) speculative rollback over the whole pool: add ``delta`` (N,)
    — ``accepted_width - W``, zero for free slots — to every layer's cache
    index. Stale K/V beyond the restored index stay in the buffers but the
    offset causal mask already hides positions ``>= index`` from all later
    reads, and the next real write overwrites them in place (the same
    invariant ``ops.attention.rollback_cache`` documents; the pool variant
    is arithmetic on the stacked index vector so it stays ONE jitted
    program)."""
    return [dict(c, index=c["index"] + delta) for c in pool_caches]


@partial(jax.jit, static_argnames=("cfg", "chunk"))
def _slot_prefill(
    params, pool_caches, slot, prompt, start, cfg: ModelConfig, chunk: int
):
    """Prefill a (1, n) prompt suffix into slot ``slot`` at absolute
    positions ``start .. start + n - 1`` (slot AND start traced — no
    recompile per slot or per prefix-cache hit length), resetting the
    slot's cache index to ``start``. ``start`` is 0 for a plain admission;
    a prefix-cache hit restores ``start`` positions first
    (``_slot_restore``) and prefills only the unmatched suffix from there.
    Returns ((1, V) logits for the next position, updated pool caches).

    NOT donated, unlike ``_pool_step``: an execution-time failure here (e.g.
    device OOM on a long prompt) is answered as a per-request admission
    error and the pool keeps serving — donated inputs would already be
    invalidated, so the next step would dereference deleted buffers and kill
    every in-flight request. ``_pool_step`` failures are fatal anyway, so
    the hot per-token path keeps the in-place donation win."""
    slot_caches = jax.tree.map(lambda x: x[slot], pool_caches)
    slot_caches = [dict(c, index=jnp.asarray(start, jnp.int32)) for c in slot_caches]
    logits, slot_caches = transformer_prefill(
        params, prompt, None, None, slot_caches, start, cfg, chunk=chunk
    )
    pool_caches = jax.tree.map(
        lambda pool, s: pool.at[slot].set(s), pool_caches, slot_caches
    )
    return logits, pool_caches


@jax.jit
def _slot_restore(pool_caches, slot, blocks):
    """Copy prefix-cache blocks (per-layer host buffers, already
    ``device_put`` by jit's argument transfer) into slot ``slot`` at
    positions ``[0, width)`` — the NO-FORWARD half of a cache-hit
    admission. ``blocks`` is padded to a power-of-two block count
    (``PrefixHit.stacked``), so the compile set is O(log(max_total /
    block)), never one per hit length; zero pad rows land at positions the
    offset causal mask hides until the suffix prefill overwrites them.
    Cache ``index`` is untouched here — ``_slot_prefill`` resets it to the
    restored width when it ingests the suffix. NOT donated, for the same
    per-request admission-error isolation as ``_slot_prefill``."""
    slot_caches = jax.tree.map(lambda x: x[slot], pool_caches)
    slot_caches = [
        insert_kv_blocks(c, b, 0) for c, b in zip(slot_caches, blocks)
    ]
    return jax.tree.map(
        lambda pool, s: pool.at[slot].set(s), pool_caches, slot_caches
    )


@partial(jax.jit, static_argnames=("n",))
def _slot_read_blocks(pool_caches, slot, start, n: int):
    """Read ``n`` KV rows at ``[start, start + n)`` from slot ``slot`` in
    storage layout (``ops.attention.slice_kv_blocks``) — the retirement-side
    export the prefix cache host-copies into its trie. ``n`` is the static
    block width, so this compiles ONCE; ``start``/``slot`` are traced."""
    slot_caches = jax.tree.map(lambda x: x[slot], pool_caches)
    return [slice_kv_blocks(c, start, n) for c in slot_caches]


# --------------------------------------------------------------------------
# paged KV layout (--kv_layout paged): ONE block pool per layer, per-slot
# block tables (kernels/kv_pool.py). The jitted programs below are the
# paged twins of the dense _pool_step/_pool_verify/_slot_prefill family:
# each gathers the slots' dense-ORDERED views through the table (sliced to
# the dense buffer length, so every attention reduction keeps the dense
# shape), runs the SAME vmapped model forward the dense pool runs, and
# scatters only the newly written rows back into the pool — greedy and
# seeded-sampled answers are bit-identical paged vs dense because the
# compute graph consumes identical values at every unmasked position
# (stale gathered rows sit at positions the offset causal mask already
# hides, the invariant recycled dense slots rely on too). Per-slot cache
# indices are HOST-authoritative in paged mode (rebuilt from st.pos each
# call, like the pick positions), so rollback is pure table truncation.


def _paged_views(pool_caches, table, index, buf_len: int):
    """Per-layer stacked slot views, structurally identical to the dense
    SlotPool pytree: leaves (N, 1, buf_len, H, D) + per-slot ``index``."""
    from transformer_tpu.kernels.kv_pool import gather_block_views

    views = []
    for layer in pool_caches:
        view = {
            key: gather_block_views(layer[key], table, buf_len)[:, None]
            for key in kv_buffer_keys(layer)
        }
        view["index"] = index
        views.append(view)
    return views


def _paged_scatter(pool_caches, new_views, table, index, s_q: int,
                   block_tokens: int):
    """Write the rows the forward just produced — per slot, positions
    ``[index, index + s_q)`` of its view — back into the pool buffers, in
    storage layout (the view's buffers were written by the same _store_kv
    the dense path uses, so the pool rows are bit-identical to a dense
    cache's). Free slots (index 0, all-sink tables) land in the sink."""
    from transformer_tpu.kernels.kv_pool import block_row_ids, scatter_rows

    n = table.shape[0]
    rids = block_row_ids(table, index, s_q, block_tokens).reshape(-1)
    out = []
    for layer, view in zip(pool_caches, new_views):
        new = dict(layer)
        for key in kv_buffer_keys(layer):
            rows = jax.vmap(
                lambda v, i: jax.lax.dynamic_slice_in_dim(v[0], i, s_q, axis=0)
            )(view[key], index)  # (N, s_q, ...)
            new[key] = scatter_rows(
                layer[key], rids, rows.reshape(n * s_q, *rows.shape[2:])
            )
        out.append(new)
    return out


@partial(
    jax.jit,
    static_argnames=("cfg", "block_tokens", "buf_len"),
    donate_argnums=(1,),
)
def _pool_step_paged(
    params, pool_caches, table, index, toks, cfg: ModelConfig,
    block_tokens: int, buf_len: int,
):
    """Paged ``_pool_step``: gather views -> the SAME vmapped batch-1
    decode step -> scatter each slot's one new row back into its block."""
    views = _paged_views(pool_caches, table, index, buf_len)

    def one(tok, caches):
        pos = caches[0]["index"]
        logits, caches = transformer_decode_step(
            params, tok[None, None], None, None, caches, pos, cfg
        )
        return logits[0], caches

    logits, new_views = jax.vmap(one)(toks, views)
    return logits, _paged_scatter(
        pool_caches, new_views, table, index, 1, block_tokens
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "block_tokens", "buf_len"),
    donate_argnums=(1,),
)
def _pool_verify_paged(
    params, pool_caches, table, index, toks, cfg: ModelConfig,
    block_tokens: int, buf_len: int,
):
    """Paged ``_pool_verify``: W-wide rows through the same static-shape
    verify forward; rejected tails are erased by HOST table truncation
    (blocks return to the pool), not a device index rollback."""
    views = _paged_views(pool_caches, table, index, buf_len)

    def one(tok_row, caches):
        pos = caches[0]["index"]
        logits, caches = transformer_verify(
            params, tok_row[None, :], caches, pos, cfg
        )
        return logits[0], caches

    logits, new_views = jax.vmap(one)(toks, views)
    return logits, _paged_scatter(
        pool_caches, new_views, table, index, toks.shape[1], block_tokens
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "block_tokens", "interpret"),
    donate_argnums=(1,),
)
def _pool_step_paged_flash(
    params, pool_caches, table, index, toks, cfg: ModelConfig,
    block_tokens: int, interpret: bool,
):
    """``_pool_step_paged`` on the fused kernels (--decode_kernel
    paged_flash): one batched forward whose attention reads pool blocks in
    place through the table — no gathered view, no per-slot vmap — and
    whose dense-FFN sublayers run as single Pallas kernels
    (``models/paged_decode.py``). Same signature family as the gather twin
    minus ``buf_len`` (nothing dense-ordered exists to size)."""
    logits, new_pools = paged_decode_forward(
        params, toks[:, None], pool_caches, table, index, cfg,
        block_tokens=block_tokens, interpret=interpret,
    )
    return logits[:, 0], new_pools


@partial(
    jax.jit,
    static_argnames=("cfg", "block_tokens", "interpret"),
    donate_argnums=(1,),
)
def _pool_verify_paged_flash(
    params, pool_caches, table, index, toks, cfg: ModelConfig,
    block_tokens: int, interpret: bool,
):
    """``_pool_verify_paged`` on the fused kernels: W-wide speculative
    rows scored in one forward — the paged-flash kernel's per-row offset
    causality handles S_q = k + 1 directly (the gather-flash path's S_q=1
    restriction does not apply). Rejected tails still roll back by HOST
    table truncation, exactly like the gather twin."""
    logits, new_pools = paged_decode_forward(
        params, toks, pool_caches, table, index, cfg,
        block_tokens=block_tokens, interpret=interpret,
    )
    return logits, new_pools


@partial(
    jax.jit, static_argnames=("cfg", "chunk", "block_tokens", "buf_len")
)
def _slot_prefill_paged(
    params, pool_caches, table, slot, prompt, start, cfg: ModelConfig,
    chunk: int, block_tokens: int, buf_len: int,
):
    """Paged ``_slot_prefill``: one slot's gathered view through the same
    chunked prefill, then scatter the written suffix rows ``[start, start
    + n)`` into the slot's blocks. ``slot`` and ``start`` stay traced (no
    recompile per slot or hit length); NOT donated, for the same
    admission-error isolation as the dense prefill."""
    from transformer_tpu.kernels.kv_pool import gather_block_views, scatter_rows

    row = jax.lax.dynamic_slice_in_dim(table, slot, 1, axis=0)  # (1, nmax)
    views = [
        {
            key: gather_block_views(layer[key], row, buf_len)
            for key in kv_buffer_keys(layer)
        }
        for layer in pool_caches
    ]
    caches = [dict(v, index=jnp.asarray(start, jnp.int32)) for v in views]
    logits, caches = transformer_prefill(
        params, prompt, None, None, caches, start, cfg, chunk=chunk
    )
    n = prompt.shape[1]
    nmax = table.shape[1]
    pos = start + jnp.arange(n)
    blk = jnp.take(row[0], jnp.clip(pos // block_tokens, 0, nmax - 1))
    rids = blk * block_tokens + pos % block_tokens
    new_pool = []
    for layer, c in zip(pool_caches, caches):
        new = dict(layer)
        for key in kv_buffer_keys(layer):
            rows = jax.lax.dynamic_slice_in_dim(c[key], start, n, axis=1)[0]
            new[key] = scatter_rows(layer[key], rids, rows)
        new_pool.append(new)
    return logits, new_pool


@jax.jit
def _pool_write_blocks(pool_caches, bids, blocks):
    """Write host-format prefix blocks into pool blocks ``bids`` — the
    paged restore for HOST-tier hits (and the warm-up/disaggregation
    inject path). ``blocks`` is per-layer dicts of (n_pad, B, H, D)
    buffers in storage layout; ``bids`` is padded to a power-of-two count
    with sink ids + zero rows (compile set O(log pool), never one per hit
    length). Device-tier hits never reach here — they are pure table
    aliasing with zero host<->device copies."""
    out = []
    for layer, b in zip(pool_caches, blocks):
        new = dict(layer)
        for key in kv_buffer_keys(layer):
            new[key] = layer[key].at[bids].set(b[key])
        out.append(new)
    return out


@jax.jit
def _pool_read_block(pool_caches, bid):
    """One pool block in host prefix-cache format: per-layer dicts of
    (1, B, H, D) storage-layout buffers — byte-compatible with the dense
    ``_slot_read_blocks`` export, so spill-to-host, ``--disaggregate``
    KV handoff, and supervisor cache-warming keep their wire format."""
    return [
        {
            key: jax.lax.dynamic_slice_in_dim(layer[key], bid, 1, axis=0)[0][
                None
            ]
            for key in kv_buffer_keys(layer)
        }
        for layer in pool_caches
    ]


@jax.jit
def _pool_copy_blocks(pool_caches, src, dst):
    """Device-side block copies for copy-on-write splits: ``src``/``dst``
    id vectors padded to a power of two with (sink, sink) no-op pairs."""
    out = []
    for layer in pool_caches:
        new = dict(layer)
        for key in kv_buffer_keys(layer):
            new[key] = layer[key].at[dst].set(layer[key][src])
        out.append(new)
    return out


def version_value(tag: "str | None") -> float:
    """Stable numeric rendering of a weight_version tag for the
    ``serve_weight_version`` gauge (gauges are floats; the digest is hex).
    crc32 keeps it exactly representable in a float64 and stable across
    processes. 0.0 = untagged."""
    return float(zlib.crc32(str(tag).encode())) if tag else 0.0


def _pow2_pad(ids: list[int], fill: int = 0) -> list[int]:
    """Pad an id list to the next power-of-two length (bounded compile
    set for the block-granular device ops)."""
    n = max(1, len(ids))
    p = 1
    while p < n:
        p *= 2
    return list(ids) + [fill] * (p - len(ids))


def abstract_paged_pool(
    cfg: ModelConfig, num_slots: int, max_total: int,
    pool_blocks: int, block_tokens: int,
):
    """The paged pool's device layout as ShapeDtypeStructs — per-layer
    block-pool buffers plus the (num_slots, slot_blocks) table and (N,)
    index — the ONE statement the abstract analyses (contracts, costs)
    share with what ``SlotPool(kv_layout="paged")`` actually allocates."""
    from transformer_tpu.ops.attention import init_block_pool

    pool = jax.eval_shape(
        lambda: [
            init_block_pool(
                pool_blocks, block_tokens, cfg.kv_heads, cfg.head_dim,
                cfg.compute_dtype, quantize=cfg.kv_cache_int8,
            )
            for _ in range(cfg.num_layers)
        ]
    )
    slot_blocks = -(-max_total // block_tokens)
    table = jax.ShapeDtypeStruct((num_slots, slot_blocks), np.int32)
    index = jax.ShapeDtypeStruct((num_slots,), np.int32)
    return pool, table, index


@partial(jax.jit, static_argnames=("sample", "top_k", "top_p"))
def _pick_pool(logits, base_keys, positions, temperatures, *, sample, top_k, top_p):
    """Per-slot next-token picks over the whole pool (fixed shape — one
    compile per distinct static sampling signature, not per occupancy).
    Each slot's rng is ``fold_in(base_key, position)`` — the same
    position-keyed folding ``lm_generate`` uses, so picks match sequential
    serving bit for bit."""

    def one(row_logits, base_key, position, temperature):
        key = jax.random.fold_in(base_key, position)
        return sample_token(
            row_logits[None], key, sample=sample, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )[0]

    return jax.vmap(one)(logits, base_keys, positions, temperatures)


@partial(jax.jit, static_argnames=("sample", "top_k", "top_p"))
def _pick_pool_verify(
    logits, base_keys, positions, temperatures, *, sample, top_k, top_p
):
    """Per-slot, per-position picks over a verify step's (N, W, V) logits
    -> (N, W) tokens: ``speculative.verify_row_picks`` (the ONE definition
    of the position-keyed verify-pick math — ``fold_in(base_key, position
    + j)``, same folding as ``_pick_pool``/``lm_generate``) vmapped over
    the slot axis, so a slot whose drafts all miss still draws exactly
    what sequential serving would draw at each absolute position."""

    def one(row_logits, base_key, position, temperature):
        return verify_row_picks(
            row_logits, base_key, position, temperature,
            sample=sample, top_k=top_k, top_p=top_p,
        )

    return jax.vmap(one)(logits, base_keys, positions, temperatures)


@partial(jax.jit, static_argnames=("sample", "top_k", "top_p"))
def _pick_one(logits, base_key, position, temperature, *, sample, top_k, top_p):
    """Single-row pick for the prefill edge (prompt fully ingested — the
    prefill's last logits are the first generation tick's logits)."""
    key = jax.random.fold_in(base_key, position)
    return sample_token(
        logits, key, sample=sample, temperature=temperature,
        top_k=top_k, top_p=top_p,
    )[0]


@dataclasses.dataclass
class _Pending:
    """One queued (not-yet-admitted) request."""

    order: int
    req: dict
    t_enqueue: float
    # Absolute perf_counter deadline (submit time + deadline_ms), or None.
    # Parsed leniently at submit — an unconvertible deadline_ms stays None
    # here and raises the validation error at admission, where it answers
    # this request alone.
    deadline: float | None = None
    # Bounded-retry state for transient admission faults: attempts so far,
    # and the jittered-backoff timestamp before which admit() must not
    # re-try this entry.
    attempts: int = 0
    not_before: float = 0.0
    # Tracing (None when the scheduler has no tracer): the request's root
    # span (submit -> answer) and the currently-open lifecycle child.
    # span_admit/span_prefill ride here only during an admission attempt,
    # so a transient-fault retry (or an admission error) can close them.
    span_root: object = None
    span_queue: object = None
    span_admit: object = None
    span_prefill: object = None


@dataclasses.dataclass
class _Active:
    """Host-side state of one occupied slot."""

    order: int                 # request arrival index (output ordering)
    ids: list[int]             # BOS-led prompt token ids
    prompt_len: int
    pos: int                   # next position to consume (== cache index)
    cur: int                   # token to feed at the next pool step
    emitted: list[int]
    max_new: int
    key: np.ndarray            # base PRNG key (request seed)
    sample: bool
    temperature: float
    top_k: int
    top_p: float
    seed: int = 0              # raw seed (rejection-sampling acceptance rng)
    # Speculative decoding (scheduler-level k > 0): whether THIS request
    # drafts (per-request "speculate": false opts out — it still rides the
    # W-wide verify step, just with no lookahead candidates), the drafter's
    # per-request state, and the accounting behind acceptance-rate /
    # tokens-per-forward telemetry.
    spec: bool = False
    dstate: object = None
    drafted: int = 0
    accepted: int = 0
    forwards: int = 0          # target-model decode forwards this request rode
    # Prefix cache: whether this request participates (per-request
    # "cache_prefix": false opts out of BOTH reading and feeding the trie)
    # and how many prompt positions were restored from stored blocks
    # instead of a model forward (span field; hit-rate in obs summarize).
    use_prefix: bool = False
    prefix_hit: int = 0
    # Admission-time weight_version tag (None on an untagged scheduler):
    # stamped at admission and carried onto the answer/span — a request
    # that straddles an upgrade still reports (and was served by) the
    # weights it was admitted under.
    wv: "str | None" = None
    # Span clock (host perf_counter; None until the edge is reached):
    # enqueue -> admit -> prefill-dispatched -> first token -> finish.
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_prefill: float | None = None
    t_first: float | None = None
    # Absolute perf_counter deadline (None = no deadline): checked at the
    # queue, prefill, and decode-step boundaries; expiry frees the slot and
    # answers a structured "deadline" error with the partial continuation.
    deadline: float | None = None
    # Tracing spans (None without a tracer): the root rides over from the
    # _Pending; prefill closes when the LAST prompt token is in cache
    # (exactly the t_prefill edge) and decode opens there.
    span_root: object = None
    span_prefill: object = None
    span_decode: object = None

    @property
    def trace_id(self) -> "str | None":
        return None if self.span_root is None else self.span_root.ctx.trace_id


class SlotPool:
    """A fixed pool of per-slot decoder KV storage: stacked dense caches
    (``kv_layout="dense"``, the historical layout) or ONE block pool per
    layer shared by every slot through block tables (``"paged"``,
    kernels/kv_pool.py — resident KV proportional to used tokens)."""

    def __init__(
        self,
        cfg: ModelConfig,
        num_slots: int,
        max_total: int,
        *,
        kv_layout: str = "dense",
        kv_block: int = 16,
        kv_pool_blocks: int = 0,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}"
            )
        self.num_slots = num_slots
        self.max_total = max_total
        self.layout = kv_layout
        self.alloc = None
        if kv_layout == "paged":
            if cfg.attention_window:
                # The windowed-refusal variant: a rolling buffer stores
                # position p at slot p % buf_len and evicts on wrap, so
                # absolute-position block rows are neither stable nor
                # complete — same policy as the prefix cache and
                # speculative rollback.
                raise ValueError(
                    "kv_layout='paged' cannot serve a rolling-window cache "
                    "(attention_window evicts absolute-position rows on "
                    "wrap); serve this config with kv_layout='dense'"
                )
            from transformer_tpu.kernels.kv_pool import KVPool
            from transformer_tpu.ops.attention import init_block_pool

            if kv_block < 1:
                raise ValueError(f"kv_block must be >= 1, got {kv_block}")
            self.block_tokens = kv_block
            self.slot_blocks = -(-max_total // kv_block)
            # Views gather at nmax*B rows then slice to max_total, so the
            # attention reduction keeps the DENSE buffer shape (a bitwise-
            # parity precondition).
            self.buf_len = max_total
            # 0 = full provisioning (every slot can always reach max_total
            # — zero behavior change vs dense, the safe default); smaller
            # pools bound resident KV by used tokens and lean on the spill
            # /preemption ladder under pressure.
            num_blocks = kv_pool_blocks or (1 + num_slots * self.slot_blocks)
            self.alloc = KVPool(
                num_blocks, kv_block, num_slots, self.slot_blocks
            )
            self.caches = [
                init_block_pool(
                    num_blocks, kv_block, cfg.kv_heads, cfg.head_dim,
                    cfg.compute_dtype, quantize=cfg.kv_cache_int8,
                )
                for _ in range(cfg.num_layers)
            ]
            return
        per_slot = [
            init_decoder_caches(cfg, 1, max_total) for _ in range(num_slots)
        ]
        # Stack to a leading slot axis: k/v (N, 1, buf, H, D), index (N,).
        self.caches = jax.tree.map(
            lambda *xs: jnp.stack(xs), per_slot[0], *per_slot[1:]
        )


class ContinuousScheduler:
    """Step-level continuous-batching scheduler for decoder-only exports.

    ``submit`` queues LM requests (dicts with ``prompt`` and the optional
    ``max_new`` / ``temperature`` / ``top_k`` / ``top_p`` / ``seed`` fields
    the grouped path accepts); ``submit_done`` reserves an output position
    for an already-answered response (parse/routing errors) so ordering is
    preserved across both. ``admit``/``step``/``drain_ready`` are the
    streaming API the serve CLI drives; ``run`` is the batch convenience
    the tests (and one-shot callers) use.
    """

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        tokenizer,
        *,
        num_slots: int = 8,
        max_total: int | None = None,
        prefill_chunk: int = 0,
        default_max_new: int = 64,
        telemetry=None,
        speculate_k: int = 0,
        drafter=None,
        prefix_cache=None,
        max_backlog: int = 0,
        admission_retries: int = 2,
        retry_backoff_ms: float = 20.0,
        drafter_slow_ms: float = 0.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
        breaker_clock=time.monotonic,
        slos=None,
        span_tap=None,
        kv_layout: str = "dense",
        kv_block: int = 16,
        kv_pool_blocks: int = 0,
        decode_kernel: str = "xla",
        weight_version: "str | None" = None,
        mesh: "int | str | None" = None,
    ):
        if not cfg.decoder_only:
            raise ValueError(
                "continuous batching serves decoder-only LM exports; seq2seq "
                "and fill-mask requests go through the grouped path"
            )
        if speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, got {speculate_k}")
        if speculate_k and cfg.attention_window:
            raise ValueError(
                "speculative decoding cannot roll back a rolling-window "
                "cache (attention_window evicts slots that stay in-window "
                "after rollback); serve this config with speculate_k=0"
            )
        if prefix_cache is not None and cfg.attention_window:
            # Mirrors the speculative refusal above: block restore addresses
            # cache rows by absolute position, which a rolling buffer evicts
            # on wrap (PrefixCache's own constructor refuses too — this
            # guards a cache built against a different config).
            raise ValueError(
                "prefix cache cannot serve a rolling-window cache "
                "(attention_window evicts absolute-position rows on wrap); "
                "serve this config without --prefix_cache_mb"
            )
        self.params, self.cfg, self.tok = params, cfg, tokenizer
        # ---- live-weights control plane (serve/upgrade.py) ----------------
        # The TWO-VERSION param slot: `params` serves; a staged
        # (params, version) pair waits for the quiesce drain; after a swap
        # the displaced pair stays resident in `_prev` so rollback is an
        # O(1) re-stage of buffers that never left the device. While a
        # stage is pending, admission pauses (the local quiesce — the
        # router has already stopped dispatching) so every in-flight
        # request finishes on its ADMISSION-TIME weights; the flip happens
        # at the next drained step boundary and compiles nothing: the new
        # params are structure/shape/dtype-verified twins, so every jitted
        # program re-runs its existing executable with new operand values.
        self.weight_version = weight_version
        self._staged: "tuple | None" = None        # (params, version)
        self._prev: "tuple | None" = None          # the resident old pair
        self._swap_events: "deque[dict]" = deque() # worker-loop outbox
        self.prefix_cache = prefix_cache
        self.prefill_chunk = prefill_chunk
        self.default_max_new = default_max_new
        self.max_total = max_total or cfg.max_position + 1
        self.speculate_k = speculate_k
        # k > 0 with no drafter given: the model-free n-gram drafter (zero
        # extra params/forwards — the safe default).
        self.drafter = (
            drafter if drafter is not None or not speculate_k else NgramDrafter()
        )
        # speculate_k rows of buffer slack: a verify step writes W = k + 1
        # positions even when the slot sits at its very last budgeted
        # position — the slack keeps those writes in-bounds (a clamped
        # dynamic_update_slice would silently shift the write over REAL
        # prefix positions). Admission budgets still use max_total.
        if kv_layout == "paged" and prefix_cache is not None:
            # Pool blocks and prefix-cache blocks must be the SAME unit:
            # a device-tier hit aliases trie-held pool blocks straight
            # into the slot's table.
            kv_block = prefix_cache.block_tokens
        # ---- sharded replica (serve/sharded.py, --mesh) -------------------
        # mesh = N makes this scheduler a pjit program over an N-device
        # serving mesh: params replicated by the partition rules, pool KV
        # sharded on its leading storage axis, every canned program
        # re-jitted with explicit in/out shardings (the _fn_* dispatch
        # below). mesh = None is the historical single-device path,
        # byte-for-byte untouched.
        from transformer_tpu.serve.sharded import parse_mesh_spec

        self.mesh_size = parse_mesh_spec(mesh)
        self._sharded = None
        if self.mesh_size is not None:
            if decode_kernel == "paged_flash":
                raise ValueError(
                    "decode_kernel='paged_flash' is a single-device fused-"
                    "kernel program (models/paged_decode.py reads pool "
                    "blocks in place); serve --mesh replicas with the "
                    "gather-view programs (decode_kernel='xla')"
                )
            if num_slots % self.mesh_size:
                raise ValueError(
                    f"num_slots={num_slots} must divide the serving mesh "
                    f"(data={self.mesh_size}): the pool shards on the slot "
                    "axis, and a ragged shard would fail at the first "
                    "dispatch instead of here"
                )
            if kv_layout == "paged":
                # The paged pool shards on the block-row axis: round the
                # pool up to a multiple of the mesh so every shard holds
                # the same number of block rows. The extra rows just sit
                # on the allocator's free list.
                slot_blocks = -(-(self.max_total + speculate_k) // kv_block)
                blocks = kv_pool_blocks or (1 + num_slots * slot_blocks)
                kv_pool_blocks = blocks + (-blocks) % self.mesh_size
        self.pool = SlotPool(
            cfg, num_slots, self.max_total + speculate_k,
            kv_layout=kv_layout, kv_block=kv_block,
            kv_pool_blocks=kv_pool_blocks,
        )
        self.paged = self.pool.layout == "paged"
        # ---- decode kernel selection (--decode_kernel) --------------------
        # "xla": the gather-view programs — the bitwise parity reference and
        # the fallback for every config. "paged_flash": the fused Pallas
        # programs (models/paged_decode.py) that read pool blocks in place;
        # paged layout only, and the config guards are static so a bad combo
        # fails at construction, not at the first step. Off-TPU the kernels
        # run in interpret mode — resolved ONCE here so the flag is a static
        # jit arg (one executable per scheduler, not per backend probe).
        if decode_kernel not in ("xla", "paged_flash"):
            raise ValueError(
                f"decode_kernel must be 'xla' or 'paged_flash', got "
                f"{decode_kernel!r}"
            )
        if decode_kernel == "paged_flash":
            if not self.paged:
                raise ValueError(
                    "decode_kernel='paged_flash' reads the block-pool "
                    "buffers in place and needs kv_layout='paged'"
                )
            check_paged_flash_config(cfg)
        self.decode_kernel = decode_kernel
        self._kernel_interpret = jax.default_backend() != "tpu"
        # ---- program dispatch: module-level jits or sharded twins ---------
        # Unsharded schedulers dispatch the module-level programs (shared
        # compile caches across schedulers — the retrace budgets pin them);
        # a sharded scheduler dispatches its own pjit twins with explicit
        # in/out shardings over the serving mesh. Same signatures, same
        # statics, same donation — call sites below never branch.
        if self.mesh_size is not None:
            from transformer_tpu.serve.sharded import (
                ShardedPrograms,
                serving_mesh,
            )

            self._mesh = serving_mesh(self.mesh_size)
            sp = self._sharded = ShardedPrograms(self._mesh, self.params)
            self.params = sp.place_params(self.params)
            self.pool.caches = sp.place_pool(self.pool.caches)
            self._fn_pool_step = sp.pool_step
            self._fn_pool_verify = sp.pool_verify
            self._fn_pool_rollback = sp.pool_rollback
            self._fn_slot_prefill = sp.slot_prefill
            self._fn_slot_restore = sp.slot_restore
            self._fn_slot_read_blocks = sp.slot_read_blocks
            self._fn_pool_step_paged = sp.pool_step_paged
            self._fn_pool_verify_paged = sp.pool_verify_paged
            self._fn_slot_prefill_paged = sp.slot_prefill_paged
            self._fn_pool_write_blocks = sp.pool_write_blocks
            self._fn_pool_read_block = sp.pool_read_block
            self._fn_pool_copy_blocks = sp.pool_copy_blocks
        else:
            self._mesh = None
            self._fn_pool_step = _pool_step
            self._fn_pool_verify = _pool_verify
            self._fn_pool_rollback = _pool_rollback
            self._fn_slot_prefill = _slot_prefill
            self._fn_slot_restore = _slot_restore
            self._fn_slot_read_blocks = _slot_read_blocks
            self._fn_pool_step_paged = _pool_step_paged
            self._fn_pool_verify_paged = _pool_verify_paged
            self._fn_slot_prefill_paged = _slot_prefill_paged
            self._fn_pool_write_blocks = _pool_write_blocks
            self._fn_pool_read_block = _pool_read_block
            self._fn_pool_copy_blocks = _pool_copy_blocks
        if self.paged and prefix_cache is not None:
            # Device-resident prefix tier: retiring slots donate their
            # prompt blocks by aliasing (refcount, zero copies), hits
            # alias back, and pool pressure spills LRU device blocks to
            # the host trie in the existing wire format.
            prefix_cache.attach_device_pool(
                self.pool.alloc, self._read_pool_block
            )
        self.num_slots = num_slots
        self._free = list(range(num_slots))
        self._active: dict[int, _Active] = {}
        self._queue: deque[_Pending] = deque()
        self._done: dict[int, dict] = {}
        self._next_order = 0
        self._emit_next = 0
        # Intake lock: submit/submit_done allocate output orders and append
        # to the queue from CLIENT threads (the multi-replica router has
        # several); admission/stepping stay single-threaded on the
        # scheduler's own loop.
        self._intake_lock = threading.Lock()
        # shutdown() flips this: late submissions (the router's redispatch
        # window can race a draining replica) answer a structured
        # "routing" error instead of queueing into a loop nobody drives.
        self._closed = False
        # Orders whose cancellation was requested (order -> message):
        # registered from ANY thread under the intake lock, EXECUTED by the
        # scheduler loop at the next step boundary (_expire) — the queue
        # answers, _active dict, slot pool, and stats are owned by the
        # scheduler thread, so a client thread never mutates them.
        self._cancel_pending: dict[int, str] = {}
        # Queued entries carrying a deadline (maintained under the intake
        # lock at every queue add/remove): lets the per-step expiry sweep
        # skip its O(backlog) queue scan entirely in the common
        # no-deadlines case, like the _cancel_pending guard below.
        self._queued_deadlines = 0
        # ---- resilience knobs (docs/ROBUSTNESS.md) ------------------------
        self.max_backlog = max_backlog          # 0 = unbounded (historical)
        self.admission_retries = max(0, admission_retries)
        self.retry_backoff_ms = retry_backoff_ms
        self.drafter_slow_ms = drafter_slow_ms
        # Circuit breakers: fail speculation / prefix reuse OPEN to the
        # plain byte-parity path after `threshold` consecutive faults; one
        # half-open probe per `cooldown_s` decides recovery. Both always
        # exist (record/allow are cheap) so degraded-mode logic has one
        # shape with or without telemetry.
        self._brk_spec = CircuitBreaker(
            "speculative", threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s, clock=breaker_clock,
            on_transition=self._on_breaker_transition,
        )
        self._brk_prefix = CircuitBreaker(
            "prefix_cache", threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s, clock=breaker_clock,
            on_transition=self._on_breaker_transition,
        )
        self.breakers = {b.name: b for b in (self._brk_spec, self._brk_prefix)}
        self.stats = {
            "admitted": 0, "steps": 0, "max_active": 0,
            # Prefix-cache accounting (host-side, filled at admission):
            # prompt tokens seen, tokens restored from stored blocks, and
            # the prefill forwards actually dispatched — decode_bench's
            # --prefix_reuse sweep derives "forwards saved" from these.
            "prompt_tokens": 0, "prefix_hit_tokens": 0, "prefill_forwards": 0,
            # Paged-KV accounting (kv_layout="paged"): prompt tokens whose
            # restore was pure device-side block-table ALIASING vs tokens
            # restored through a host block copy, slots preempted on pool
            # exhaustion (answered "resource"), and device blocks spilled
            # to the host trie under pool pressure.
            "prefix_alias_tokens": 0, "host_restored_tokens": 0,
            "kv_preempted": 0, "kv_spilled_blocks": 0,
            # Resilience accounting (telemetry-free introspection for the
            # chaos suite): transient-admission retries, deadline expiries,
            # client cancellations, backpressure refusals.
            "retries": 0, "deadline_expired": 0, "cancelled": 0,
            "backpressure": 0,
        }
        # Telemetry (obs.Telemetry | None) records host-side scalars only, at
        # the step/admission boundaries that already exist — answers stay
        # byte-identical (tests/test_obs.py pins this) and the decode hot
        # path compiles the same programs (retrace budget stays 0).
        self._tel = telemetry
        # Tracing rides the telemetry bundle (Telemetry(trace=True) /
        # --trace); None disables every span site at one attribute check.
        self._tracer = getattr(telemetry, "tracer", None)
        # Per-program dispatch profiler (obs/profile.py, armed via
        # Telemetry.arm_profiler): clocks each canned program under the
        # SAME base names the cost model prices, so the roofline report
        # can join measured against predicted. The program this scheduler
        # dispatches is fixed at construction by layout + kernel choice.
        self._profiler = getattr(telemetry, "profiler", None)
        _kind = (
            "_paged_flash"
            if self.paged and self.decode_kernel == "paged_flash"
            else "_paged" if self.paged else ""
        )
        self._prog_step = "serve.pool_step" + _kind
        self._prog_verify = "serve.pool_verify" + _kind
        self._prog_prefill = "serve.slot_prefill" + (
            "_paged" if self.paged else ""
        )
        # Victim attribution for breaker transitions: the trace id of the
        # request whose fault is being recorded, set around the fallible
        # regions (admission, retirement feed, drafting) on the scheduler
        # thread — _on_breaker_transition stamps it into serve.breaker
        # events so a chaos episode reconstructs as one trace tree.
        self._breaker_trace: str | None = None
        # SLO engine (obs/slo.py): burn-rate evaluation over the answer
        # stream. `slos` is a spec tuple or an --slo_spec string; needs
        # telemetry (gauges + slo.burn events are its whole output).
        # Span tap: an optional host-side callable handed every answer-
        # boundary span dict (the same payload `serve.request` events and
        # the SLO engine see) WITHOUT requiring a telemetry bundle — the
        # replica worker uses it to ship per-answer ttft/prefix numbers to
        # the router's own SLO engine over the wire (serve/replica.py).
        # Host-side only, never traced: jaxpr-inert by construction.
        self._span_tap = span_tap
        self._slo = None
        if telemetry is not None and slos:
            from transformer_tpu.obs.slo import SLOEngine, parse_slo_spec

            specs = parse_slo_spec(slos) if isinstance(slos, str) else tuple(slos)
            if specs:
                self._slo = SLOEngine(
                    specs, registry=telemetry.registry, emit=telemetry.emit
                )
        if telemetry is not None:
            reg = telemetry.registry
            self._m_slots_total = reg.gauge(
                "serve_slots_total", "configured KV-cache slots")
            self._m_slots_total.set(num_slots)
            self._m_weight_version = reg.gauge(
                "serve_weight_version",
                "crc32 of the serving weight_version tag (0 = untagged); "
                "flips exactly at the double-buffered param swap")
            self._m_weight_version.set(version_value(weight_version))
            self._m_active = reg.gauge(
                "serve_slots_active", "slots occupied by in-flight requests")
            self._m_backlog = reg.gauge(
                "serve_backlog", "submitted-but-not-admitted requests")
            self._m_ready = reg.gauge(
                "serve_ready", "completed responses awaiting drain")
            self._m_requests = reg.counter(
                "serve_requests_total", "requests submitted (incl. errors)")
            self._m_admissions = reg.counter(
                "serve_admissions_total", "requests admitted into a slot")
            self._m_retirements = reg.counter(
                "serve_retirements_total", "requests finished and retired")
            self._m_errors = reg.counter(
                "serve_errors_total", "requests answered with an error")
            self._m_steps = reg.counter(
                "serve_steps_total", "pool decode steps executed")
            self._m_tokens = reg.counter(
                "serve_generated_tokens_total", "tokens emitted to clients")
            self._m_queue_s = reg.histogram(
                "serve_queue_seconds", "submit -> slot admission")
            self._m_prefill_s = reg.histogram(
                "serve_prefill_seconds", "admission -> prompt ingested")
            self._m_ttft_s = reg.histogram(
                "serve_ttft_seconds", "submit -> first generated token")
            self._m_total_s = reg.histogram(
                "serve_request_seconds", "submit -> response complete")
            self._m_step_s = reg.histogram(
                "serve_step_seconds", "one pool step (all slots, one token)")
            if speculate_k:
                self._m_spec_drafted = reg.counter(
                    "serve_spec_drafted_total",
                    "draft tokens proposed to verify steps")
                self._m_spec_accepted = reg.counter(
                    "serve_spec_accepted_total",
                    "draft tokens the target model accepted")
                self._m_spec_rejected = reg.counter(
                    "serve_spec_rejected_total",
                    "draft tokens rejected or wasted past a mismatch")
            if prefix_cache is not None:
                self._m_prefix_hit = reg.counter(
                    "serve_prefix_hit_tokens_total",
                    "prompt tokens restored from the prefix cache "
                    "(no model forward)")
                self._m_prefix_evicted = reg.counter(
                    "serve_prefix_evicted_blocks_total",
                    "prefix-cache KV blocks evicted under the byte budget")
            if self.paged:
                self._m_pool_used = reg.gauge(
                    "serve_kv_pool_used_blocks",
                    "paged KV pool blocks referenced by live slots or the "
                    "device-resident prefix tier")
                self._m_pool_free = reg.gauge(
                    "serve_kv_pool_free_blocks",
                    "paged KV pool blocks on the free list")
                self._m_pool_used.set(self.pool.alloc.used_blocks)
                self._m_pool_free.set(self.pool.alloc.free_blocks)
                if prefix_cache is not None:
                    self._m_alias_tokens = reg.counter(
                        "serve_prefix_alias_tokens_total",
                        "prompt tokens served by device-side block-table "
                        "aliasing (zero host<->device copies) — a subset "
                        "of serve_prefix_hit_tokens_total; the remainder "
                        "was restored through a host block copy")
            self._m_deadline = reg.counter(
                "serve_deadline_expired_total",
                "requests answered with a deadline error")
            self._m_cancelled = reg.counter(
                "serve_cancelled_total", "requests cancelled by the client")
            self._m_backpressure = reg.counter(
                "serve_backpressure_total",
                "requests refused at submit (max_backlog)")
            self._m_retries = reg.counter(
                "serve_admission_retries_total",
                "transient admission faults retried with backoff")

    # ---- request intake ---------------------------------------------------

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        """Breaker state -> obs: a gauge (0 closed / 1 half-open / 2 open)
        plus a ``serve.breaker`` event per transition — `obs summarize`
        derives degraded-time from the event stream, and the event carries
        the trace id of the request whose fault tripped it (when tracing).
        Host-side only; no-op without telemetry."""
        if self._tel is None:
            return
        self._tel.registry.gauge(
            f"serve_breaker_state_{name}",
            "circuit-breaker state: 0 closed, 1 half-open, 2 open",
        ).set(BREAKER_STATE_VALUE[new])
        extra = {}
        if self._breaker_trace is not None:
            extra["trace"] = self._breaker_trace
        self._tel.emit(
            "serve.breaker", name=name, state=new, previous=old, **extra
        )

    # ---- tracing / SLO plumbing -------------------------------------------

    def _traced(self, name: str, parent, **attrs):
        """A ``tracer.span`` context (explicit parent — request-lifecycle
        spans must tie to THEIR request's tree, never to whatever step span
        happens to be current), or a no-op when tracing is off."""
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.span(name, parent=parent, **attrs)

    def _record_request(self, span: dict, root=None) -> None:
        """The one answer-boundary funnel: every ``serve.request`` span
        event goes through here so the trace id is stamped uniformly and
        the SLO engine sees exactly what the log sees."""
        if root is not None:
            span.setdefault("trace", root.ctx.trace_id)
        if self._slo is not None:
            self._slo.record(dict(span))
        if self._span_tap is not None:
            self._span_tap(dict(span))
        if self._tel is not None:
            self._tel.emit("serve.request", **span)

    @staticmethod
    def _end_spans(obj, attrs: "tuple[str, ...]", **fields) -> None:
        """Close any still-open spans named by ``attrs`` on a _Pending or
        _Active (defensive: every error path funnels through one of the
        answer helpers, and a span left open would fail the completeness
        tests)."""
        for attr in attrs:
            sp = getattr(obj, attr, None)
            if sp is not None:
                sp.end(**fields)
                setattr(obj, attr, None)

    def _trace_prefill_done(self, st: _Active) -> None:
        """The prompt is fully in cache: close the prefill span and open
        the decode span — called exactly where ``t_prefill`` is finalized
        (admission for full-prefill requests, the boundary step for
        chunked/tail-fed ones)."""
        if st.span_prefill is not None:
            st.span_prefill.end(prompt_tokens=st.prompt_len,
                                prefix_hit_tokens=st.prefix_hit)
            st.span_prefill = None
            st.span_decode = self._tracer.start_span(
                "serve.decode", parent=st.span_root, lane=st.span_root.lane
            )

    # ---- paged-KV plumbing (kv_layout="paged") ----------------------------

    def _read_pool_block(self, bid: int):
        """Fetch ONE pool block to host prefix-cache format — the only
        host<->device block copy the paged prefix tier ever pays, and only
        for spill-under-pressure or a wire export (--disaggregate handoff,
        supervisor cache warming). The device-resident HIT path never
        reaches here (pinned by test)."""
        return jax.device_get(
            self._fn_pool_read_block(self.pool.caches, jnp.int32(bid))
        )

    def _paged_alloc(self, fn):
        """Run an allocator mutation with ONE spill-and-retry rung: on
        pool exhaustion, ask the prefix cache's device tier to release
        LRU blocks (their data spills to the host trie in the wire format
        first), then retry. Re-raises ``KVPoolExhausted`` when the pool
        is genuinely full of live slots — admission maps that to a
        retryable transient, the step path to a preemption."""
        from transformer_tpu.kernels.kv_pool import KVPoolExhausted

        try:
            return fn()
        except KVPoolExhausted:
            if self.prefix_cache is None:
                raise
            freed = self.prefix_cache.release_device_blocks(
                max(1, self.pool.slot_blocks)
            )
            self.stats["kv_spilled_blocks"] += freed
            if not freed:
                raise
            return fn()

    def _paged_ensure(self, slot: int, tokens: int) -> None:
        """Grow ``slot``'s block table to cover ``tokens`` positions."""
        self._paged_alloc(lambda: self.pool.alloc.ensure(slot, tokens))

    def _paged_cow(self, slot: int, start: int, end: int) -> None:
        """Copy-on-write guard before writing positions ``[start, end)``:
        any table block shared with the device tier (or another slot) is
        split — fresh block allocated, contents copied ON DEVICE, table
        updated — before the write dispatches. Serving flows only write
        past the block-aligned aliased prefix, so this is normally a
        no-op; it is the guard that makes aliasing safe by construction."""
        pairs = self._paged_alloc(
            lambda: self.pool.alloc.make_writable(slot, start, end)
        )
        if pairs:
            src = jnp.asarray(_pow2_pad([s for s, _ in pairs]), jnp.int32)
            dst = jnp.asarray(_pow2_pad([d for _, d in pairs]), jnp.int32)
            self.pool.caches = self._fn_pool_copy_blocks(
                self.pool.caches, src, dst
            )

    def _paged_restore(self, slot: int, hit, m: int) -> int:
        """Paged restore of a matched ``m``-token prefix: device-tier
        nodes ALIAS their pool block into the slot's table (zero model
        forwards, zero host<->device copies); host-tier nodes take a
        fresh block and ride ONE batched scatter write (then the device
        tier adopts the written block, so the NEXT hit aliases). Returns
        the aliased token count."""
        B = self.pool.block_tokens
        alloc = self.pool.alloc
        aliased = 0
        host_bids: list[int] = []
        host_payload: list = []  # per restored block: per-layer dicts
        adopt: list = []
        for node, bid, blocks in hit.paged_plan():
            if bid is not None:
                self._paged_alloc(lambda b=bid: alloc.extend(slot, bid=b))
                aliased += B
            else:
                _, new_bid = self._paged_alloc(lambda: alloc.extend(slot))
                host_bids.append(new_bid)
                host_payload.append(blocks)
                adopt.append((node, new_bid))
        if host_bids:
            bids = _pow2_pad(host_bids)
            pad = len(bids) - len(host_bids)
            stacked = [
                {
                    key: np.concatenate(
                        [np.asarray(blk[li][key]) for blk in host_payload]
                        + [np.zeros_like(host_payload[0][li][key])] * pad,
                        axis=0,
                    )
                    for key in host_payload[0][li]
                }
                for li in range(len(host_payload[0]))
            ]
            self.pool.caches = self._fn_pool_write_blocks(
                self.pool.caches, jnp.asarray(bids, jnp.int32), stacked
            )
            for node, bid in adopt:
                self.prefix_cache.adopt_device(node, bid)
        # Stats are recorded by the caller at admission SUCCESS (next to
        # prefix_hit_tokens): counting here would double-count retried
        # admissions and break the alias <= hit invariant.
        return aliased

    def _paged_prepare(self, width: int) -> None:
        """Before a paged step: every occupied slot needs blocks covering
        its write range ``[pos, pos + width)``, CoW-split where shared.
        Pool exhaustion (after the spill ladder) preempts the REQUESTING
        slot with a structured ``resource`` answer carrying its partial
        continuation — bounded degradation, never a corrupted neighbor."""
        from transformer_tpu.kernels.kv_pool import KVPoolExhausted

        for slot, st in list(self._active.items()):
            try:
                self._paged_ensure(slot, st.pos + width)
                self._paged_cow(slot, st.pos, st.pos + width)
            except KVPoolExhausted as e:
                self.stats["kv_preempted"] += 1
                self._abort(
                    slot, st, "resource",
                    f"kv pool exhausted after {len(st.emitted)} of "
                    f"{st.max_new} tokens: {e}",
                )

    def _paged_gauges(self) -> None:
        if self.paged and self._tel is not None:
            self._m_pool_used.set(self.pool.alloc.used_blocks)
            self._m_pool_free.set(self.pool.alloc.free_blocks)

    # ---- live weights: the two-version param slot (serve/upgrade.py) ------

    def stage_params(self, params, version: str) -> None:
        """Stage a new weight set for the double-buffered swap. The new
        pytree must be a structural twin of the serving one — same
        treedef, same per-leaf shapes AND dtypes — so the flip re-runs
        every compiled program with new operand values and **zero
        recompiles**; any mismatch raises here, before anything is
        scheduled, and serving is untouched. While a stage is pending,
        admission pauses (the local quiesce): every in-flight request
        finishes on its admission-time weights, and the flip happens at
        the next drained step boundary (:meth:`step`)."""
        cur = jax.tree_util.tree_flatten_with_path(self.params)
        new = jax.tree_util.tree_flatten_with_path(params)
        if jax.tree_util.tree_structure(self.params) != (
            jax.tree_util.tree_structure(params)
        ):
            raise ValueError(
                f"staged weights for version {version!r} have a different "
                "pytree structure than the serving params — a swap would "
                "recompile (or crash) every program; refuse it"
            )
        mismatched = []
        for (path, a), (_, b) in zip(cur[0], new[0]):
            a_s, b_s = np.shape(a), np.shape(b)
            a_d = getattr(a, "dtype", np.asarray(a).dtype)
            b_d = getattr(b, "dtype", np.asarray(b).dtype)
            if a_s != b_s or a_d != b_d:
                key = "/".join(str(getattr(p, "key", p)) for p in path)
                mismatched.append(f"{key}: {b_s}/{b_d} != {a_s}/{a_d}")
        if mismatched:
            raise ValueError(
                f"staged weights for version {version!r} mismatch the "
                f"serving spec on {len(mismatched)} leaf/leaves "
                f"({'; '.join(mismatched[:3])}) — refused before any swap "
                "was scheduled"
            )
        if self._sharded is not None:
            # Sharded replica: the twin check grows SHARDING specs. Staged
            # leaves already committed to a device layout must match the
            # serving mesh's partition rules — a pytree living on a
            # different mesh would reshard (or crash) at the flip, so it
            # is refused here with serving untouched; host-loaded arrays
            # (the checkpoint path) pass and are committed below, keeping
            # the swap zero-recompile.
            bad = self._sharded.check_staged_shardings(params)
            if bad:
                raise ValueError(
                    f"staged weights for version {version!r} carry sharding "
                    f"specs incompatible with the serving mesh "
                    f"(data={self.mesh_size}) on {len(bad)} leaf/leaves "
                    f"({'; '.join(bad[:3])}) — refused before any swap was "
                    "scheduled"
                )
            params = self._sharded.place_params(params)
        self._staged = (params, str(version))

    def stage_rollback(self) -> str:
        """Re-stage the resident PREVIOUS weights (the second buffer a
        completed swap left behind): the canary-rollback path. Returns the
        version being rolled back to; raises when no swap ever landed."""
        if self._prev is None:
            raise ValueError(
                "no resident previous weights to roll back to (no swap has "
                "completed on this scheduler)"
            )
        params, version = self._prev
        self._staged = (params, version)
        return version

    @property
    def swap_pending(self) -> bool:
        return self._staged is not None

    def consume_swap_events(self) -> "list[dict]":
        """Drain completed/aborted swap notifications (the replica worker
        forwards them to the router as ``upgraded`` messages)."""
        out = list(self._swap_events)
        self._swap_events.clear()
        return out

    def _maybe_swap(self) -> None:
        """The step-boundary flip: only once the pool is DRAINED (every
        in-flight request answered from its admission-time weights) does
        the staged pair become the serving pair; the displaced pair stays
        resident for O(1) rollback. The ``ckpt.swap`` fault point fires
        here — an injected failure aborts the swap with the old weights
        still serving and zero requests disturbed."""
        if self._staged is None or self._active:
            return
        params, version = self._staged
        self._staged = None
        try:
            maybe_fail("ckpt.swap")
        except OSError as e:
            # InjectedFault (and any real OS-level swap veto) aborts the
            # swap, never the scheduler: old weights keep serving and the
            # worker reports the failure upstream.
            self._swap_events.append({
                "ok": False, "version": version,
                "error": f"{type(e).__name__}: {e}",
            })
            return
        self._prev = (self.params, self.weight_version)
        self.params = params
        self.weight_version = version
        self._swap_events.append({"ok": True, "version": version})
        if self._tel is not None:
            self._m_weight_version.set(version_value(version))

    def submit(self, req: dict) -> int:
        now = time.perf_counter()
        # Root span BEFORE the lock (id generation is not free): parents
        # under an incoming W3C "traceparent" when the request carries one
        # — the cross-process hook the router tier rides. Invalid headers
        # degrade to a fresh trace (W3C semantics), never an error.
        root = queue_span = None
        if self._tracer is not None:
            from transformer_tpu.obs.trace import SpanContext

            root = self._tracer.start_span(
                "serve.request", lane="intake",
                parent=SpanContext.from_traceparent(req.get("traceparent")),
            )
            queue_span = self._tracer.start_span(
                "serve.queue", parent=root, lane="intake"
            )
        refused = None  # the refusal message, captured INSIDE the lock —
        # reading self._done[order] back after release would race the
        # scheduler thread's drain_ready() popping it.
        refused_code = "backpressure"
        with self._intake_lock:
            order = self._next_order
            self._next_order += 1
            if self._closed:
                # Post-shutdown submission (the router's redispatch path
                # hits this window): answer NOW with a structured routing
                # error — queueing would strand the request in a loop that
                # will never admit again.
                refused = (
                    "scheduler is shut down and accepts no new requests; "
                    "resubmit to a live replica"
                )
                refused_code = "routing"
                self._done[order] = error_answer(refused_code, refused)
            elif self.max_backlog and len(self._queue) >= self.max_backlog:
                # Bounded admission backpressure: refuse NOW with a
                # structured error instead of queueing without bound — the
                # client sees a retryable condition while in-flight
                # requests keep their latency.
                self.stats["backpressure"] += 1
                refused = (
                    f"admission queue is full ({self.max_backlog} "
                    "requests); retry after a backoff"
                )
                self._done[order] = error_answer("backpressure", refused)
            else:
                deadline = None
                try:
                    d = req.get("deadline_ms")
                    if d is not None:
                        deadline = now + float(d) / 1e3
                except (TypeError, ValueError):
                    pass  # _start re-parses and answers the validation error
                self._queue.append(
                    _Pending(order=order, req=req, t_enqueue=now,
                             deadline=deadline, span_root=root,
                             span_queue=queue_span)
                )
                if deadline is not None:
                    self._queued_deadlines += 1
        if refused is not None and root is not None:
            queue_span.end(error=refused)
            root.end(order=order, error=refused, code=refused_code)
        if self._tel is not None:
            self._m_requests.inc()
            if refused is not None:
                if refused_code == "backpressure":
                    self._m_backpressure.inc()
                self._m_errors.inc()
                self._record_request(
                    {"order": order, "total_s": 0.0, "error": refused,
                     "code": refused_code},
                    root=root,
                )
        return order

    def submit_done(self, resp: dict) -> int:
        root = None
        if self._tracer is not None:
            # Pre-answered (parse/routing) responses still get a (leaf)
            # span: every output order is accounted for in the trace.
            root = self._tracer.start_span("serve.request", lane="intake")
        with self._intake_lock:
            order = self._next_order
            self._next_order += 1
            self._done[order] = resp
        if root is not None:
            extra = {}
            if "error" in resp:
                extra["error"] = resp["error"]
                if "code" in resp:  # taxonomy code, like every error root
                    extra["code"] = resp["code"]
            root.end(order=order, **extra)
        if self._tel is not None:
            self._m_requests.inc()
            if "error" in resp:
                self._m_errors.inc()
            span = {"order": order, "total_s": 0.0}
            if "error" in resp:
                span["error"] = resp["error"]
                if "code" in resp:
                    span["code"] = resp["code"]
            self._record_request(span, root=root)
        return order

    def cancel(self, order: int, message: str = "cancelled by client") -> bool:
        """Request cancellation of a queued or in-flight request. The
        cancellation is REGISTERED here (any thread, intake lock only) and
        EXECUTED by the scheduler loop at the next step boundary: the queue
        entry is dropped or the slot freed, and a structured "cancelled"
        error answers at the request's reserved output position, so
        arrival-order draining is unaffected and no prefix-cache pin can be
        left behind (admission releases its hit synchronously). Returns
        False when ``order`` is unknown, already answered, or already being
        cancelled; True means the cancellation will be honored unless the
        request completes first (it answers exactly once either way — the
        benign race of cancelling a finishing request)."""
        with self._intake_lock:
            if (
                order in self._done            # answered, not yet drained
                or order >= self._next_order   # never submitted
                or order < self._emit_next     # answered and drained
                or order in self._cancel_pending
            ):
                return False
            self._cancel_pending[order] = message
        return True

    def _answer_cancelled(self, p: _Pending, message: str) -> None:
        """Answer a queued (never-admitted) cancellation — scheduler
        thread only, like every other queue answer."""
        self.stats["cancelled"] += 1
        self._done[p.order] = error_answer("cancelled", message)
        root = p.span_root
        self._end_spans(p, ("span_queue", "span_admit", "span_prefill"))
        self._end_spans(
            p, ("span_root",), order=p.order, error=message, code="cancelled"
        )
        if self._tel is not None:
            now = time.perf_counter()
            self._m_cancelled.inc()
            self._m_errors.inc()
            span = {"order": p.order, "error": message, "code": "cancelled"}
            if p.t_enqueue:
                span["queue_s"] = round(now - p.t_enqueue, 6)
                span["total_s"] = round(now - p.t_enqueue, 6)
            self._record_request(span, root=root)

    @property
    def busy(self) -> bool:
        return bool(self._queue or self._active)

    @property
    def has_ready(self) -> bool:
        """True when ``drain_ready`` would release at least one response."""
        return self._emit_next in self._done

    @property
    def ready_count(self) -> int:
        """Completed-but-not-drained responses (includes out-of-order
        completions waiting behind the arrival-order emit head). The serve
        loop counts these toward its ingest cap so a flood of instantly
        answered lines — e.g. all-malformed input — cannot grow the host-side
        buffer without bound."""
        return len(self._done)

    @property
    def backlog(self) -> int:
        """Submitted-but-not-admitted requests (the serve loop bounds this
        so stdin backpressure survives — see ``cli/serve.py``)."""
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return len(self._active)

    # ---- admission --------------------------------------------------------

    def admit(self) -> None:
        """Fill free slots from the queue (prefill-into-slot). A request
        that fails validation/encoding answers with its error alone — it
        never enters the pool, so it cannot poison co-batched requests.
        Transient faults (:class:`TransientError`, e.g. an injected prefill
        fault or a flaky device) get up to ``admission_retries`` re-tries
        with jittered exponential backoff before answering a structured
        "transient" error; entries whose backoff has not elapsed are
        skipped this tick, not dropped."""
        if self._staged is not None:
            # Quiesce: a staged weight swap is waiting for the pool to
            # drain. New admissions would re-fill it with requests pinned
            # to the OLD weights and starve the swap — queued requests
            # wait (deadline/cancel sweeps still run at step boundaries)
            # and admission resumes the moment the flip lands.
            return
        now = time.perf_counter()
        deferred: list[_Pending] = []
        while self._free:
            with self._intake_lock:
                # Pops (and the extendleft below) take the intake lock so
                # cancel()'s queue scan from a client thread never observes
                # a deque mutating under its iteration.
                if not self._queue:
                    break
                p = self._queue.popleft()
                if p.deadline is not None:
                    self._queued_deadlines -= 1
            if p.not_before > now:
                deferred.append(p)
                continue
            if p.deadline is not None and now >= p.deadline:
                self._answer_expired(p, now)
                continue
            with self._intake_lock:
                cancel_msg = self._cancel_pending.pop(p.order, None)
            if cancel_msg is not None:
                # Registered cancel caught before admission: answer without
                # ever paying the prefill (or taking a slot).
                self._answer_cancelled(p, cancel_msg)
                continue
            try:
                self._start(p)
            except TransientError as e:
                if p.attempts < self.admission_retries:
                    p.attempts += 1
                    wait_ms = backoff_ms(
                        self.retry_backoff_ms, p.attempts - 1, p.order
                    )
                    p.not_before = now + wait_ms / 1e3
                    deferred.append(p)
                    self.stats["retries"] += 1
                    # Spans opened by the failed attempt close with the
                    # fault; the request goes back to queueing, so a fresh
                    # queue span covers the backoff wait.
                    self._end_spans(
                        p, ("span_admit", "span_prefill"),
                        error=f"{type(e).__name__}: {e}", retried=True,
                    )
                    if self._tracer is not None and p.span_queue is None:
                        p.span_queue = self._tracer.start_span(
                            "serve.queue", parent=p.span_root, lane="intake",
                            attempt=p.attempts,
                        )
                    if self._tel is not None:
                        self._m_retries.inc()
                        retry_ev = {
                            "order": p.order, "attempt": p.attempts,
                            "backoff_ms": round(wait_ms, 3),
                            "error": f"{type(e).__name__}: {e}",
                        }
                        if p.span_root is not None:
                            retry_ev["trace"] = p.span_root.ctx.trace_id
                        self._tel.emit("serve.retry", **retry_ev)
                    continue
                self._answer_admission_error(p, e, now)
            except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — per-request isolation: ANY admission failure must answer this request alone, never kill co-batched ones
                self._answer_admission_error(p, e, now)
        # Backoff-deferred entries return to the FRONT in arrival order:
        # output order is fixed by `order` anyway, this just keeps queue
        # scans (deadline expiry, cancel) seeing them.
        if deferred:
            with self._intake_lock:
                self._queue.extendleft(reversed(deferred))
                self._queued_deadlines += sum(
                    1 for p in deferred if p.deadline is not None
                )

    def _answer_admission_error(
        self, p: _Pending, e: BaseException, now: float
    ) -> None:
        code = classify_error(e)
        self._done[p.order] = error_answer(code, f"{type(e).__name__}: {e}")
        root = p.span_root
        self._end_spans(
            p, ("span_queue", "span_admit", "span_prefill"),
            error=type(e).__name__,
        )
        self._end_spans(
            p, ("span_root",), order=p.order,
            error=self._done[p.order]["error"], code=code,
        )
        if self._tel is not None:
            t_enq = p.t_enqueue
            self._m_errors.inc()
            self._record_request(
                {
                    "order": p.order,
                    "queue_s": round(now - t_enq, 6) if t_enq else 0.0,
                    "total_s": round(now - t_enq, 6) if t_enq else 0.0,
                    "error": self._done[p.order]["error"],
                    "code": code,
                },
                root=root,
            )

    def _answer_expired(self, p: _Pending, now: float) -> None:
        """A queued request's deadline elapsed before a slot freed."""
        self.stats["deadline_expired"] += 1
        self._done[p.order] = error_answer(
            "deadline",
            f"deadline_ms elapsed after {round((now - p.t_enqueue) * 1e3)}ms "
            "in the admission queue",
        )
        root = p.span_root
        self._end_spans(p, ("span_queue", "span_admit", "span_prefill"))
        self._end_spans(
            p, ("span_root",), order=p.order,
            error=self._done[p.order]["error"], code="deadline",
        )
        if self._tel is not None:
            self._m_deadline.inc()
            self._m_errors.inc()
            self._record_request(
                {
                    "order": p.order,
                    "queue_s": round(now - p.t_enqueue, 6),
                    "total_s": round(now - p.t_enqueue, 6),
                    "error": self._done[p.order]["error"],
                    "code": "deadline",
                },
                root=root,
            )

    def _expire(self, now: float) -> None:
        """Deadline sweep at a step boundary: queued requests whose
        deadline passed answer without ever taking a slot; in-flight ones
        free their slot mid-generation (the emitted prefix rides along as
        ``"partial"``)."""
        expired_q: list[_Pending] = []
        if self._queued_deadlines:
            with self._intake_lock:
                # Scan under the intake lock: client threads append to the
                # deque concurrently, and deque ITERATION (unlike popleft/
                # append) is not atomic. Answers are emitted after release —
                # telemetry takes locks of its own. The _queued_deadlines
                # guard keeps this O(backlog) scan off the per-step path
                # when no queued request carries a deadline.
                expired_q = [
                    p for p in self._queue
                    if p.deadline is not None and now >= p.deadline
                ]
                for p in expired_q:
                    self._queue.remove(p)
                    self._queued_deadlines -= 1
        for p in expired_q:
            self._answer_expired(p, now)
        if self._cancel_pending:
            with self._intake_lock:
                pending = dict(self._cancel_pending)
                cancelled_q = [
                    p for p in self._queue if p.order in pending
                ]
                for p in cancelled_q:
                    self._queue.remove(p)
                    if p.deadline is not None:
                        self._queued_deadlines -= 1
        else:
            pending, cancelled_q = {}, []
        for p in cancelled_q:
            self._answer_cancelled(p, pending[p.order])
        for slot, st in list(self._active.items()):
            if st.order in pending:
                # Cancellation registered by cancel() (any thread),
                # executed here on the scheduler thread that owns the pool.
                self._abort(slot, st, "cancelled", pending[st.order])
            elif st.deadline is not None and now >= st.deadline:
                self._abort(
                    slot, st, "deadline",
                    f"deadline_ms elapsed after {len(st.emitted)} of "
                    f"{st.max_new} tokens",
                )
        if pending:
            # Retire executed/answered registrations; one mid-admission at
            # this instant (popped from the queue, not yet in _active)
            # stays pending and is swept right after its admission lands.
            # An order that completed normally before its sweep was simply
            # answered once, normally — the benign race cancel() documents.
            with self._intake_lock:
                for order in pending:
                    if order in self._done or order < self._emit_next:
                        self._cancel_pending.pop(order, None)

    def _abort(self, slot: int, st: _Active, code: str, message: str) -> None:
        """Free an occupied slot WITHOUT normal retirement (deadline expiry
        or cancellation): the slot returns to the pool (admission resets
        its cache index, so stale K/V is provably invisible to the next
        occupant), nothing is fed to the prefix cache, and the request
        answers a structured error carrying whatever was generated so far.
        No prefix-cache pins can be outstanding here — admission releases
        its hit synchronously before the request ever reaches a step
        boundary."""
        del self._active[slot]
        if self.paged:
            self.pool.alloc.free_slot(slot)
        self._free.append(slot)
        resp = error_answer(code, message)
        if st.emitted:
            resp["partial"] = _detokenize_rows(
                np.asarray([st.emitted], np.int32), 1, self.tok
            )[0]
        if st.wv is not None:
            resp["weight_version"] = st.wv
        self._done[st.order] = resp
        if code == "deadline":
            self.stats["deadline_expired"] += 1
        elif code == "cancelled":
            self.stats["cancelled"] += 1
        root = st.span_root
        self._end_spans(st, ("span_prefill", "span_decode"))
        self._end_spans(
            st, ("span_root",), order=st.order, error=message, code=code,
            new_tokens=len(st.emitted),
        )
        if self._tel is not None:
            now = time.perf_counter()
            if code == "deadline":
                self._m_deadline.inc()
            elif code == "cancelled":
                self._m_cancelled.inc()
            self._m_errors.inc()
            span = {
                "order": st.order,
                "prompt_tokens": st.prompt_len,
                "new_tokens": len(st.emitted),
                "queue_s": round(st.t_admit - st.t_enqueue, 6),
                "total_s": round(now - st.t_enqueue, 6),
                "error": message,
                "code": code,
            }
            if st.wv is not None:
                span["weight_version"] = st.wv
            self._record_request(span, root=root)

    def _start(self, p: _Pending) -> None:
        """Admission wrapper: breaker-fault attribution (set by the inner
        body) must not outlive the admission — a stale trace id would be
        stamped onto the NEXT cooldown-driven breaker transition, blaming
        an unrelated request."""
        try:
            self._start_inner(p)
        finally:
            self._breaker_trace = None

    def _start_inner(self, p: _Pending) -> None:
        order, req, t_enq = p.order, p.req, p.t_enqueue
        maybe_fail("serve.prefill")  # chaos point: admission-time fault
        if self._tracer is not None:
            # The queue phase ends here (a retry re-opens it); everything
            # from validation through the first pick is the admit span.
            # Faults from here on feed breakers under this request's name.
            self._end_spans(p, ("span_queue",))
            p.span_admit = self._tracer.start_span(
                "serve.admit", parent=p.span_root, lane="intake"
            )
            self._breaker_trace = p.span_root.ctx.trace_id
        prompt = str(req["prompt"])
        ids = [self.tok.bos_id, *self.tok.encode(prompt)]
        L = len(ids)
        if L >= self.cfg.max_position:
            # Same failure mode (and message shape) as generate().
            raise ValueError(
                f"a prompt encodes to {L} tokens but the model's "
                f"max_position is {self.cfg.max_position}; shorten the prompt"
            )
        max_new = int(req.get("max_new", self.default_max_new))
        max_new = min(max_new, self.cfg.max_position - L)
        if L + 1 >= self.max_total:
            raise ValueError(
                f"a prompt encodes to {L} tokens but the slot budget "
                f"(serve_max_total) is {self.max_total}; shorten the prompt "
                "or raise --serve_max_total"
            )
        max_new = min(max_new, self.max_total - 1 - L)
        deadline = None
        if req.get("deadline_ms") is not None:
            # float() raising (e.g. "soon") answers a validation error for
            # this request alone, like every other unconvertible field.
            deadline = (
                (t_enq or time.perf_counter())
                + float(req["deadline_ms"]) / 1e3
            )
        temperature = float(req.get("temperature", 0.0))
        sample = temperature > 0.0
        # Greedy never touches the rng or the truncation params: normalize
        # them (mirroring _signature's grouped path) so stray values neither
        # change the answer nor split step()'s pick groups into extra
        # byte-identical argmax compiles.
        top_k = int(req.get("top_k", 0)) if sample else 0
        top_p = float(req.get("top_p", 1.0)) if sample else 1.0
        seed = int(req.get("seed", 0)) if sample else 0
        if sample and top_k > self.cfg.target_vocab_size:
            # lax.top_k would raise INSIDE the jitted pick — validate before
            # a slot is popped so the bad request answers alone (the grouped
            # path's per-member retry answers the same line with an error).
            raise ValueError(
                f"top_k={top_k} exceeds the vocab size "
                f"{self.cfg.target_vocab_size}"
            )
        if req.get("cache_prefix") and self.cfg.attention_window:
            # An EXPLICIT cache_prefix=true on a rolling-window server is a
            # contract the server cannot honor (block restore addresses
            # rows by absolute position; the window buffer evicts them on
            # wrap) — answer this request alone with a structured error,
            # before any slot is popped, mirroring the speculative-rollback
            # refusal. Absent/false composes fine: the request just
            # prefills normally.
            raise ValueError(
                "cache_prefix=true cannot be honored: this server runs a "
                "rolling-window cache (attention_window), which the prefix "
                "cache refuses — resend with cache_prefix=false or serve "
                "without attention_window"
            )
        use_prefix = (
            self.prefix_cache is not None
            and bool(req.get("cache_prefix", True))
            # Degradation ladder: while the prefix breaker is open, opted-in
            # requests neither read nor feed the cache — they take the plain
            # byte-parity full-prefill path (answers identical either way).
            and self._brk_prefix.allow()
        )
        hit = None
        m = 0
        prefix_ok = True  # no cache fault during THIS admission
        if use_prefix:
            # Match the prompt MINUS its last token: at least one token must
            # go through the model forward — the admission pick needs
            # next-token logits, which a block restore cannot produce.
            try:
                with self._traced(
                    "prefix.match", p.span_admit, lane="intake"
                ) as msp:
                    hit = self.prefix_cache.match(ids[: L - 1])
                    m = hit.tokens
                    if msp is not None:
                        msp.set(hit_tokens=m)
            except Exception:  # noqa: BLE001  # tpa: disable=TPA006 — prefix reuse is an optional accelerator: ANY cache failure (corrupt block, injected fault, trie bug) feeds the breaker and degrades THIS admission to full prefill; it must never answer the request with an error
                self._brk_prefix.record_failure()
                prefix_ok = False
                hit, m = None, 0
        n_suffix = prefill_len_for(L - m, self.prefill_chunk)
        n = m + n_suffix
        slot = self._free.pop()
        t_admit = time.perf_counter()
        if self._tracer is not None:
            # The slot is known now: the request's remaining lifecycle
            # renders on this slot's lane (admit/queue stay on intake —
            # they are scheduler work, not slot residency).
            p.span_root.lane = f"slot{slot}"
            p.span_prefill = self._tracer.start_span(
                "serve.prefill", parent=p.span_root, lane=f"slot{slot}",
            )
        aliased = 0
        try:
            if m:
                try:
                    with self._traced(
                        "prefix.restore", p.span_prefill,
                        lane=f"slot{slot}", tokens=m,
                    ):
                        if self.paged:
                            aliased = self._paged_restore(slot, hit, m)
                        else:
                            self.pool.caches = self._fn_slot_restore(
                                self.pool.caches, jnp.int32(slot),
                                hit.stacked(self.max_total + self.speculate_k),
                            )
                except TransientError:
                    # Pool pressure (KVPoolExhausted maps below), retried
                    # faults: not the cache's fault — no breaker feed.
                    raise
                except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — same degradation contract as the match above: a failed restore falls back to full prefill (the slot's index reset makes any partial restore invisible), feeding the breaker instead of erroring the request
                    from transformer_tpu.kernels.kv_pool import KVPoolExhausted

                    if isinstance(e, KVPoolExhausted):
                        # Exhaustion mid-restore is pool pressure, not a
                        # cache fault: surface as a retryable transient.
                        raise TransientError(str(e)) from e
                    self._brk_prefix.record_failure()
                    prefix_ok = False
                    hit.release()
                    hit, m, aliased = None, 0, 0
                    if self.paged:
                        # Drop any partially-aliased table entries so the
                        # fallback full prefill starts from a clean row.
                        self.pool.alloc.free_slot(slot)
                    n_suffix = prefill_len_for(L, self.prefill_chunk)
                    n = n_suffix
            t_pf = time.perf_counter()
            if self.paged:
                from transformer_tpu.kernels.kv_pool import KVPoolExhausted

                try:
                    self._paged_ensure(slot, n)
                    self._paged_cow(slot, m, n)
                except KVPoolExhausted as e:
                    raise TransientError(str(e)) from e
                logits, self.pool.caches = self._fn_slot_prefill_paged(
                    self.params, self.pool.caches,
                    self.pool.alloc.table_device(), jnp.int32(slot),
                    jnp.asarray([ids[m:n]], jnp.int32), jnp.int32(m),
                    self.cfg, self.prefill_chunk,
                    self.pool.block_tokens, self.pool.buf_len,
                )
            else:
                logits, self.pool.caches = self._fn_slot_prefill(
                    self.params, self.pool.caches, jnp.int32(slot),
                    jnp.asarray([ids[m:n]], jnp.int32), jnp.int32(m), self.cfg,
                    self.prefill_chunk,
                )
        except Exception:
            if self.paged:
                self.pool.alloc.free_slot(slot)
            self._free.append(slot)
            raise
        finally:
            if hit is not None:
                hit.release()
        if self._profiler is not None:
            # Dispatch window (async: the device may still be prefilling —
            # timed_call's caveat applies); tokens = the suffix actually
            # fed through the forward, restored prefix excluded.
            self._profiler.record(
                self._prog_prefill, time.perf_counter() - t_pf,
                tokens=n_suffix,
            )
        if use_prefix and prefix_ok:
            # The cache served this admission end-to-end (hit or clean
            # miss): a half-open probe closes the breaker here.
            self._brk_prefix.record_success()
        self.stats["prompt_tokens"] += L
        self.stats["prefix_hit_tokens"] += m
        if self.paged:
            # Restored tokens split: m = aliased (device table op, zero
            # copies) + host-restored (one batched block write).
            self.stats["prefix_alias_tokens"] += aliased
            self.stats["host_restored_tokens"] += m - aliased
        chunk = self.prefill_chunk
        self.stats["prefill_forwards"] += (
            -(-n_suffix // chunk) if chunk > 0 else 1
        )
        if m and self._tel is not None and self.prefix_cache is not None:
            self._m_prefix_hit.inc(m)
            if aliased:
                self._m_alias_tokens.inc(aliased)
        spec = bool(self.speculate_k) and bool(req.get("speculate", True))
        st = _Active(
            order=order, ids=ids, prompt_len=L, pos=n, cur=PAD_ID,
            emitted=[], max_new=max_new,
            key=np.asarray(jax.random.PRNGKey(seed)),
            sample=sample, temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed, spec=spec,
            use_prefix=use_prefix, prefix_hit=m, wv=self.weight_version,
            dstate=(
                self.drafter.start(ids) if spec and self.drafter is not None
                else None
            ),
            t_enqueue=t_enq or t_admit, t_admit=t_admit,
            # Dispatch-time edge: under async dispatch the prefill has been
            # ENQUEUED here, not finished; the full-prefill path syncs just
            # below at the first pick, making the span exact there.
            t_prefill=time.perf_counter(),
            deadline=deadline,
            # Span ownership transfers from the _Pending to the slot state:
            # from here on, answer paths close through st, not p.
            span_root=p.span_root, span_prefill=p.span_prefill,
        )
        p.span_root = p.span_prefill = None
        self._active[slot] = st
        self.stats["max_active"] = max(self.stats["max_active"], len(self._active))
        self._end_spans(
            p, ("span_admit",), slot=slot, prefix_hit_tokens=st.prefix_hit
        )
        if deadline is not None and time.perf_counter() >= deadline:
            # Prefill-boundary deadline check: the prompt ingest alone
            # consumed the budget — answer now instead of decoding tokens
            # the client has already given up on.
            self.stats["admitted"] += 1
            if self._tel is not None:
                self._m_admissions.inc()
            self._abort(
                slot, st, "deadline", "deadline_ms elapsed during prefill"
            )
            return
        if n < L:
            st.cur = ids[n]  # un-prefilled prompt tail feeds token-by-token
        else:
            try:
                tokv = int(
                    _pick_one(
                        logits, jnp.asarray(st.key), jnp.int32(n - 1),
                        jnp.float32(st.temperature),
                        sample=st.sample, top_k=st.top_k, top_p=st.top_p,
                    )
                )
            except Exception:
                # The pick failing must not leak the slot: restore the pool
                # so the error answers this request alone (admit() catches;
                # spans travel back to the _Pending so the answer path can
                # close them).
                del self._active[slot]
                if self.paged:
                    self.pool.alloc.free_slot(slot)
                self._free.append(slot)
                p.span_root, p.span_prefill = st.span_root, st.span_prefill
                raise
            if self._tracer is not None:
                # The pick above synced the prefill: the whole prompt is in
                # cache, decoding starts now.
                self._trace_prefill_done(st)
            self._consume_pick(slot, st, tokv)
        self.stats["admitted"] += 1
        if self._tel is not None:
            self._m_admissions.inc()

    # ---- stepping ---------------------------------------------------------

    def step(self) -> None:
        """Advance every occupied slot (ONE pooled forward): one token per
        slot on the plain path, up to ``speculate_k + 1`` on the
        speculative verify path. Retires finished slots; no-op when the
        pool is idle."""
        self._expire(time.perf_counter())
        # The step-boundary weight flip: no-op unless a verified stage is
        # pending AND the expiry sweep just drained the last slot.
        self._maybe_swap()
        if self._active and self.paged:
            # Paged capacity pass BEFORE the step arrays are built: a
            # pool-exhausted slot is preempted here (answered "resource")
            # and must not be stepped.
            self._paged_prepare(self.speculate_k + 1 if self.speculate_k else 1)
        if not self._active:
            if self._tel is not None:
                self._m_active.set(0)
                self._m_backlog.set(len(self._queue))
                self._m_ready.set(len(self._done))
                self._paged_gauges()
                self._tel.maybe_flush()
                if self._slo is not None:
                    self._slo.maybe_evaluate()
            return
        if self.speculate_k:
            self._step_verify()
        else:
            self._step_plain()

    def _step_plain(self) -> None:
        t_step = time.perf_counter()
        step_span = None
        if self._tracer is not None:
            step_span = self._tracer.start_span(
                "scheduler.step", lane="scheduler",
                active=len(self._active), backlog=len(self._queue),
            )
        N = self.num_slots
        toks = np.full((N,), PAD_ID, np.int32)
        keys = np.zeros((N, *np.shape(jax.random.PRNGKey(0))), np.uint32)
        positions = np.zeros((N,), np.int32)
        temps = np.ones((N,), np.float32)
        for slot, st in self._active.items():
            toks[slot] = st.cur
            keys[slot] = st.key
            positions[slot] = st.pos
            temps[slot] = st.temperature
        if self.paged and self.decode_kernel == "paged_flash":
            logits, self.pool.caches = _pool_step_paged_flash(
                self.params, self.pool.caches,  # tpa: disable=TPA005 — exclusive if/elif/else triplet: exactly one branch runs per step and all rebind self.pool.caches from their own result
                self.pool.alloc.table_device(), jnp.asarray(positions),
                jnp.asarray(toks), self.cfg,
                self.pool.block_tokens, self._kernel_interpret,
            )
        elif self.paged:
            logits, self.pool.caches = self._fn_pool_step_paged(
                self.params, self.pool.caches,  # tpa: disable=TPA005 — exclusive if/elif/else triplet: exactly one branch runs per step and all rebind self.pool.caches from their own result
                self.pool.alloc.table_device(), jnp.asarray(positions),
                jnp.asarray(toks), self.cfg,
                self.pool.block_tokens, self.pool.buf_len,
            )
        else:
            logits, self.pool.caches = self._fn_pool_step(
                self.params, self.pool.caches, jnp.asarray(toks), self.cfg
            )
        groups: dict[tuple, list[int]] = {}
        for slot, st in self._active.items():
            groups.setdefault((st.sample, st.top_k, st.top_p), []).append(slot)
        picks: dict[int, int] = {}
        for (sample, top_k, top_p), slots in groups.items():
            out = np.asarray(
                _pick_pool(
                    logits, jnp.asarray(keys), jnp.asarray(positions),
                    jnp.asarray(temps),
                    sample=sample, top_k=top_k, top_p=top_p,
                )
            )
            for slot in slots:
                picks[slot] = int(out[slot])
        for slot, st in list(self._active.items()):
            st.pos += 1
            st.forwards += 1
            if st.pos < st.prompt_len:
                st.cur = st.ids[st.pos]  # still consuming the prompt tail
                continue
            if st.pos == st.prompt_len and not st.emitted:
                # Only reachable for a chunked (tail-fed) prompt: the step
                # that just ran ingested the FINAL prompt token (and its
                # logits feed the first pick below) — close the prefill span
                # here so it covers the whole prompt. Full-prefill slots pick
                # their first token at admission and skip this transition.
                st.t_prefill = time.perf_counter()
                if self._tracer is not None:
                    self._trace_prefill_done(st)
            self._consume_pick(slot, st, picks[slot])
        self.stats["steps"] += 1
        if step_span is not None:
            step_span.end()
        if self._tel is not None:
            # The np.asarray(_pick_pool) above was a real device sync, so
            # this window is genuine step time, not dispatch time.
            dt_step = time.perf_counter() - t_step
            self._m_step_s.observe(dt_step)
            self._m_steps.inc()
            if self._profiler is not None:
                # One token per slot that picked this step: the honest
                # token credit for a pool-step dispatch.
                self._profiler.record(
                    self._prog_step, dt_step, tokens=len(picks)
                )
            self._m_active.set(len(self._active))
            self._m_backlog.set(len(self._queue))
            self._m_ready.set(len(self._done))
            self._paged_gauges()
            self._tel.maybe_flush()
            if self._slo is not None:
                self._slo.maybe_evaluate()

    def _step_verify(self) -> None:
        """One speculative verify step: every occupied slot feeds its
        pending token plus up to ``speculate_k`` lookahead tokens — the
        un-ingested prompt tail first (teacher-forced, like chunked
        prefill), then drafter proposals — through ONE ``_pool_verify``
        forward. The longest accepted prefix is kept; the rejected tail is
        erased with an O(1) index rollback (``_pool_rollback``). Rows are
        padded to the static width W = k + 1 and free slots ride along, so
        mixed speculative/non-speculative pools never retrace. Emissions
        go through the same ``_consume_pick`` as the plain path — greedy
        answers are byte-identical to non-speculative serving
        (tests/test_speculative.py pins this)."""
        t_step = time.perf_counter()
        n_rows = len(self._active)  # rows fed at dispatch (pre-retirement)
        step_span = draft_span = None
        if self._tracer is not None:
            step_span = self._tracer.start_span(
                "scheduler.step", lane="scheduler",
                active=len(self._active), backlog=len(self._queue),
            )
            draft_span = self._tracer.start_span(
                "spec.draft", parent=step_span, lane="scheduler",
            )
        N, W = self.num_slots, self.speculate_k + 1
        toks = np.full((N, W), PAD_ID, np.int32)
        keys = np.zeros((N, *np.shape(jax.random.PRNGKey(0))), np.uint32)
        positions = np.zeros((N,), np.int32)
        temps = np.ones((N,), np.float32)
        # Degradation ladder: while the speculative breaker is open, no slot
        # drafts — rows carry only the pending token (plus any prompt tail),
        # which rides the SAME static-W verify program (zero recompiles) and
        # is byte-identical to plain stepping for greedy AND sampled
        # requests (no drafts = no rejection-sampling draws). A half-open
        # probe re-consults the drafter after the cooldown.
        spec_allowed = self.drafter is not None and self._brk_spec.allow()
        rows: dict[int, tuple[list[int], int]] = {}
        for slot, st in self._active.items():
            drafter = self.drafter if (st.spec and spec_allowed) else None
            # A drafter fault recorded below is this slot's request's fault.
            self._breaker_trace = st.trace_id
            t_draft = time.perf_counter()
            try:
                row, n_drafted = build_verify_row(
                    st.ids + st.emitted, st.pos, self.speculate_k,
                    drafter, st.dstate,
                )
            except Exception:  # noqa: BLE001  # tpa: disable=TPA006 — drafting is an optional accelerator: ANY drafter failure feeds the speculative breaker and this row degrades to no-lookahead (byte-identical answers); it must never kill the request, let alone the pool
                self._brk_spec.record_failure()
                row, n_drafted = build_verify_row(
                    st.ids + st.emitted, st.pos, self.speculate_k, None, None,
                )
            else:
                if drafter is not None:
                    draft_ms = (time.perf_counter() - t_draft) * 1e3
                    if self.drafter_slow_ms and draft_ms > self.drafter_slow_ms:
                        # A drafter that stalls past its budget is as bad as
                        # one that raises: speculation exists to SAVE time.
                        self._brk_spec.record_failure()
                    else:
                        self._brk_spec.record_success()
            rows[slot] = (row, n_drafted)
            toks[slot, : len(row)] = row
            keys[slot] = st.key
            positions[slot] = st.pos
            temps[slot] = st.temperature
        self._breaker_trace = None
        verify_span = None
        if draft_span is not None:
            draft_span.end(drafted=sum(n for _, n in rows.values()))
            verify_span = self._tracer.start_span(
                "spec.verify", parent=step_span, lane="scheduler", width=W,
            )
        if self.paged and self.decode_kernel == "paged_flash":
            logits, self.pool.caches = _pool_verify_paged_flash(
                self.params, self.pool.caches,  # tpa: disable=TPA005 — exclusive if/elif/else triplet: exactly one branch runs per step and all rebind self.pool.caches from their own result
                self.pool.alloc.table_device(), jnp.asarray(positions),
                jnp.asarray(toks), self.cfg,
                self.pool.block_tokens, self._kernel_interpret,
            )
        elif self.paged:
            logits, self.pool.caches = self._fn_pool_verify_paged(
                self.params, self.pool.caches,  # tpa: disable=TPA005 — exclusive if/elif/else triplet: exactly one branch runs per step and all rebind self.pool.caches from their own result
                self.pool.alloc.table_device(), jnp.asarray(positions),
                jnp.asarray(toks), self.cfg,
                self.pool.block_tokens, self.pool.buf_len,
            )
        else:
            logits, self.pool.caches = self._fn_pool_verify(
                self.params, self.pool.caches, jnp.asarray(toks), self.cfg
            )
        groups: dict[tuple, list[int]] = {}
        for slot, st in self._active.items():
            groups.setdefault((st.sample, st.top_k, st.top_p), []).append(slot)
        picks: dict[int, np.ndarray] = {}
        for (sample, top_k, top_p), slots in groups.items():
            out = np.asarray(
                _pick_pool_verify(
                    logits, jnp.asarray(keys), jnp.asarray(positions),
                    jnp.asarray(temps),
                    sample=sample, top_k=top_k, top_p=top_p,
                )
            )
            for slot in slots:
                picks[slot] = out[slot]
        delta = np.zeros((N,), np.int32)
        drafted = accepted = 0
        for slot, st in list(self._active.items()):
            row, n_drafted = rows[slot]
            slot_picks = picks[slot]
            if st.sample and n_drafted:
                # Rejection-sampling acceptance needs the target
                # probabilities at the draft tokens — numbers that never
                # leave the device on the plain path. Slice THIS slot's
                # (W, V) rows on device; fetching the whole (N, W, V) pool
                # tensor would tax every greedy neighbor's step latency.
                slot_logits = np.asarray(logits[slot], np.float32)
                pos0 = st.pos

                def accept(j, draft, _l=slot_logits, _st=st, _p=pos0):
                    probs = filtered_probs(
                        _l[j], _st.temperature, _st.top_k, _st.top_p
                    )
                    return sampled_accept(
                        probs, draft, keyed_rng(_st.seed, _p + j)
                    )

            else:

                def accept(j, draft, _picks=slot_picks):
                    pick = int(_picks[j])
                    return pick == draft, pick

            emitted, keep, n_accepted = judge_row(
                row, st.pos, st.prompt_len, accept,
                lambda j, _picks=slot_picks: int(_picks[j]),
            )
            st.forwards += 1
            # Count as ACCEPTED only drafts whose emissions will actually
            # be consumed — judge_row keeps judging past an EOS it cannot
            # see, and counting those would skew acceptance telemetry on
            # every finishing request. Counters must be final BEFORE the
            # consume loop: retirement emits the request's span in there.
            n_accepted = min(n_accepted, self._consumable(st, emitted))
            drafted += n_drafted
            accepted += n_accepted
            st.drafted += n_drafted
            st.accepted += n_accepted
            delta[slot] = keep - W
            st.pos += keep
            if not emitted:
                # Every fed position was still prompt: the next pending
                # token is the known prompt token at the new position.
                st.cur = st.ids[st.pos]
                continue
            if not st.emitted and st.t_prefill is not None:
                # First generated pick for a tail-fed prompt: this verify
                # ingested the final prompt token — close the prefill span
                # here, exactly like the plain path's boundary transition.
                st.t_prefill = time.perf_counter()
                if self._tracer is not None:
                    self._trace_prefill_done(st)
            for tok in emitted:
                self._consume_pick(slot, st, tok)
                if slot not in self._active:
                    break  # retired (EOS / budget): drop the row's tail
        rollback_span = None
        if verify_span is not None:
            verify_span.end(drafted=drafted, accepted=accepted)
            rollback_span = self._tracer.start_span(
                "spec.rollback", parent=step_span, lane="scheduler"
            )
        if self.paged:
            # Paged rollback IS table truncation: blocks past each slot's
            # kept width return to the pool's free list (re-ensured next
            # step), stale rows inside the kept block stay masked, and no
            # device index needs resetting — per-slot indices are rebuilt
            # from host state every call. Retired slots already freed
            # their whole row in _finish.
            for slot, st in self._active.items():
                self.pool.alloc.truncate(slot, st.pos)
        else:
            self.pool.caches = self._fn_pool_rollback(
                self.pool.caches, jnp.asarray(delta)  # tpa: disable=TPA005 — the linter's linear scan pairs this dense-branch donation with the paged verify call above; the branches are mutually exclusive and every donating call rebinds immediately
            )
        if rollback_span is not None:
            rollback_span.end()
        self.stats["steps"] += 1
        self.stats["drafted"] = self.stats.get("drafted", 0) + drafted
        self.stats["accepted"] = self.stats.get("accepted", 0) + accepted
        if step_span is not None:
            step_span.end(drafted=drafted, accepted=accepted)
        if self._tel is not None:
            dt_step = time.perf_counter() - t_step
            self._m_step_s.observe(dt_step)
            self._m_steps.inc()
            if self._profiler is not None:
                # W positions scored per fed row — the verify forward's
                # honest work unit (cost-model tokens_per_step agrees).
                self._profiler.record(
                    self._prog_verify, dt_step, tokens=n_rows * W
                )
            if drafted:
                self._m_spec_drafted.inc(drafted)
                if accepted:
                    self._m_spec_accepted.inc(accepted)
                if drafted - accepted:
                    self._m_spec_rejected.inc(drafted - accepted)
            self._m_active.set(len(self._active))
            self._m_backlog.set(len(self._queue))
            self._m_ready.set(len(self._done))
            self._paged_gauges()
            self._tel.maybe_flush()
            if self._slo is not None:
                self._slo.maybe_evaluate()

    def _consumable(self, st: _Active, emitted: list[int]) -> int:
        """How many of a verify row's emissions ``_consume_pick`` will
        consume before retiring the slot (the finishing token included) —
        a side-effect-free twin of its EOS/budget rules, used to finalize
        acceptance counters before retirement emits the request span."""
        n, cnt = 0, len(st.emitted)
        for tok in emitted:
            n += 1
            if tok == self.tok.eos_id or cnt >= st.max_new:
                break
            cnt += 1
            if cnt >= st.max_new:
                break
        return n

    def _consume_pick(self, slot: int, st: _Active, tokv: int) -> None:
        """Apply one generated token: retire on EOS or budget exhaustion,
        else schedule it as the slot's next input. The budget check runs
        BEFORE the append so max_new=0 answers with an empty continuation
        (matching generate(max_new=0))."""
        if tokv == self.tok.eos_id or len(st.emitted) >= st.max_new:
            self._finish(slot, st)
            return
        st.emitted.append(tokv)
        if st.t_first is None:
            st.t_first = time.perf_counter()
        if self._tel is not None:
            self._m_tokens.inc()
        if len(st.emitted) >= st.max_new:
            self._finish(slot, st)
        else:
            st.cur = tokv

    def _finish(self, slot: int, st: _Active) -> None:
        # Attribution BEFORE the allow() below: a cooldown-driven
        # open->half_open transition inside it belongs to this request.
        self._breaker_trace = st.trace_id
        if (
            self.prefix_cache is not None and st.use_prefix
            and self._brk_prefix.allow()
        ):
            # Feed the trie BEFORE the slot is recycled: slice the slot's
            # prompt-region KV (block-aligned; the cache's own storage
            # layout) into blocks. Only blocks the trie is missing are
            # fetched off the device — a request that fully hit fetches
            # nothing, and an unfittable budget fetches nothing either
            # (insert prechecks). Fetches are one fixed-shape dispatch per
            # missing block on purpose: slicing a whole missing RUN would
            # mint a compile per run length, trading bounded host syncs at
            # retirement for unbounded recompiles. Opted-out requests
            # neither read nor feed the cache.
            B = self.prefix_cache.block_tokens
            aligned = (st.prompt_len // B) * B
            if aligned:
                try:
                    with self._traced(
                        "prefix.insert", st.span_root,
                        lane=st.span_root.lane if st.span_root else None,
                        tokens=aligned,
                    ):
                        if self.paged:
                            # Device-tier donation: the trie ADOPTS the
                            # retiring slot's prompt blocks by reference
                            # (pool refcount) — zero device reads, zero
                            # host copies; spill-to-host happens lazily
                            # under pool pressure or a wire export.
                            evicted = self.prefix_cache.insert_device(
                                st.ids, aligned,
                                [
                                    int(b)
                                    for b in self.pool.alloc.table[slot][
                                        : aligned // B
                                    ]
                                ],
                            )
                        else:
                            evicted = self.prefix_cache.insert(
                                st.ids, aligned,
                                lambda start: jax.device_get(
                                    self._fn_slot_read_blocks(
                                        self.pool.caches, jnp.int32(slot),
                                        jnp.int32(start), B,
                                    )
                                ),
                            )
                except Exception:  # noqa: BLE001  # tpa: disable=TPA006 — feeding the trie is best-effort: a retirement-side cache fault (injected or real) feeds the breaker and this request simply does not donate its KV; its ANSWER is already computed and must still flush
                    self._brk_prefix.record_failure()
                else:
                    # Mirrors the admission path: a clean feed closes a
                    # half-open probe (without this, a breaker probed by a
                    # RETIREMENT would stay half-open, where one isolated
                    # fault re-opens it with the threshold bypassed).
                    self._brk_prefix.record_success()
                    if evicted and self._tel is not None:
                        self._m_prefix_evicted.inc(evicted)
        self._breaker_trace = None
        text = _detokenize_rows(
            np.asarray([st.emitted], np.int32) if st.emitted
            else np.zeros((1, 0), np.int32),
            1, self.tok,
        )[0]
        resp = {"continuation": text}
        if st.wv is not None:
            resp["weight_version"] = st.wv
        self._done[st.order] = resp
        del self._active[slot]
        if self.paged:
            # After donation: table references drop, aliased prompt blocks
            # live on under the device tier's refs, everything else
            # returns to the free list.
            self.pool.alloc.free_slot(slot)
        self._free.append(slot)
        root = st.span_root
        self._end_spans(st, ("span_prefill",))
        self._end_spans(st, ("span_decode",), new_tokens=len(st.emitted))
        self._end_spans(
            st, ("span_root",), order=st.order,
            prompt_tokens=st.prompt_len, new_tokens=len(st.emitted),
        )
        if self._tel is not None or self._span_tap is not None:
            now = time.perf_counter()
            queue_s = st.t_admit - st.t_enqueue
            total_s = now - st.t_enqueue
            span = {
                "order": st.order,
                "prompt_tokens": st.prompt_len,
                "new_tokens": len(st.emitted),
                "queue_s": round(queue_s, 6),
                "total_s": round(total_s, 6),
            }
            if st.forwards:
                # Decode forwards this request rode (verify or plain steps;
                # prefill excluded) — summarize derives tokens-per-forward.
                span["forwards"] = st.forwards
            if st.spec:
                span["drafted"] = st.drafted
                span["draft_accepted"] = st.accepted
            if self.prefix_cache is not None and st.use_prefix:
                # Recorded on MISSES too (0): summarize's hit rate divides
                # by prompt_tokens over participating requests only.
                span["prefix_hit_tokens"] = st.prefix_hit
            if st.wv is not None:
                span["weight_version"] = st.wv
            if st.t_prefill is not None:
                span["prefill_s"] = round(st.t_prefill - st.t_admit, 6)
            if st.t_first is not None:
                span["ttft_s"] = round(st.t_first - st.t_enqueue, 6)
            if self._tel is not None:
                self._m_queue_s.observe(queue_s)
                self._m_total_s.observe(total_s)
                if st.t_prefill is not None:
                    self._m_prefill_s.observe(st.t_prefill - st.t_admit)
                if st.t_first is not None:
                    self._m_ttft_s.observe(st.t_first - st.t_enqueue)
                self._m_retirements.inc()
            self._record_request(span, root=root)

    # ---- shutdown ---------------------------------------------------------

    def shutdown(self) -> None:
        """Stop accepting NEW work: any later :meth:`submit` answers a
        structured ``routing`` error at its reserved order instead of
        queueing into a loop nobody will drive again (the multi-replica
        router's redispatch path can race a draining replica in exactly
        this window). Everything already queued or in flight keeps its
        contract — the caller drives ``admit``/``step``/``drain_ready``
        until ``busy`` clears, exactly as before."""
        with self._intake_lock:
            self._closed = True

    # ---- output -----------------------------------------------------------

    def drain_ready(self) -> list[dict]:
        """Responses completed in arrival order (the serve loop's stdout
        contract): a response is released once every earlier request has
        answered."""
        out = []
        while self._emit_next in self._done:
            out.append(self._done.pop(self._emit_next))
            self._emit_next += 1
        return out

    def idle_backoff(self) -> None:
        """Sleep out the shortest pending retry backoff when there is
        nothing else to do (no active slots and every queued entry is
        waiting out its jittered backoff) — the drive loops would otherwise
        spin hot until the earliest ``not_before``. Bounded at 50ms so an
        arriving request is never kept waiting long."""
        if self._active or not self._queue:
            return
        now = time.perf_counter()
        with self._intake_lock:  # deque iteration vs concurrent submits
            qlen = len(self._queue)
            waits = [
                p.not_before - now for p in self._queue if p.not_before > now
            ]
        if waits and len(waits) == qlen:
            time.sleep(min(min(waits), 0.05))

    def run(self, reqs: list[dict]) -> list[dict]:
        """Drive a fixed request list to completion; returns responses in
        request order."""
        for req in reqs:
            self.submit(req)
        while self.busy:
            self.admit()
            self.step()
            self.idle_backoff()
        out = self.drain_ready()
        if self._tel is not None:
            if self._slo is not None:
                self._slo.maybe_evaluate(force=True)
            self._tel.maybe_flush(force=True)
        return out
