"""Fleet supervision: replica respawn, warm-up, and SLO-driven scaling.

PR 10 gave the serving tier horizontal scale with zero-loss failover — and
left the fleet able only to DEGRADE: a SIGKILLed replica stayed dead, the
fleet size was fixed at launch, and the router was the single point of
failure. This module closes the control loop the ROADMAP's "Pod-scale
elasticity" item names, on top of two things earlier PRs made
deterministic: the PR 7 fault plane (so every supervision decision is
drillable in CI — ``route.spawn`` can crash-loop a bootstrap on demand)
and the PR 9 SLO burn-rate engine (so fleet size is DRIVEN by the error
budget, not by a load average someone eyeballed).

Two objects, both owned by the router thread (docs/SERVING.md
"Self-healing fleet"):

- :class:`Supervisor` — replica lifecycle. When the router fails a
  replica over (pipe EOF, exit, missed heartbeats past the breaker
  cooldown), the supervisor re-bootstraps a replacement from the SAME
  deterministic spawn recipe (``--model_spec`` worker argv), under the
  replica's OLD name — rendezvous hashing therefore hands it exactly the
  affinity keys it used to own. Before the replacement takes traffic, its
  ``PrefixCache`` is warmed from a survivor over the existing
  ``export_blocks``/``inject_blocks`` wire format (``export_state`` /
  ``inject_state`` protocol messages): the respawned replica's first
  affine request is a prefix HIT, not a cold full prefill. Respawns are
  budgeted (``max_restarts`` within ``restart_window_s``, exponential
  backoff between attempts): a crash-looping bootstrap exhausts its
  budget and leaves the per-replica breaker OPEN — the fleet serves at
  N-1 instead of burning CPU on a spin, and the give-up is an explicit
  ``route.spawn`` event with ``gave_up=true``.
- :class:`FleetScaler` — fleet sizing. Consumes the burn-rate gauges of
  the router's own :class:`~transformer_tpu.obs.slo.SLOEngine` (fed by
  the answer funnel with the per-answer ``slo`` side channel the replicas
  ship): the watched signal (default ``ttft_p95``) burning > 1 for
  ``sustain_s`` sustained seconds spawns a replica (up to
  ``max_replicas``); a fleet idle for ``idle_s`` (empty backlog, zero
  in-flight, burn at 0) drains the youngest replica through the existing
  dispatch policy (mark draining -> stop offering traffic -> shutdown
  when empty) and retires it. Every decision is a ``route.scale`` event
  carrying the evidence window that justified it.

Threading contract (linted by TPA101-105; explored by
``analysis/schedules.py supervisor_respawn``): every method here runs on
the ROUTER thread (``Router.pump`` calls :meth:`Supervisor.poll` /
:meth:`FleetScaler.poll`; message handlers are dispatched from the
router's inbox drain). The spawn callable may block briefly
(``subprocess.Popen``); nothing here takes locks or touches jax — like
the router, the supervision tier is model-free host code.
"""

from __future__ import annotations

import inspect
import time

from transformer_tpu.serve.resilience import maybe_fail


class _SlotState:
    """Per-replica-index respawn bookkeeping (router-thread-owned)."""

    __slots__ = (
        "index", "name", "role", "phase", "next_try", "attempts",
        "restarts", "died_at", "warm_deadline", "warm_source",
        "postmortem_ts",
    )

    def __init__(self, index: int, name: str, role: str):
        self.index = index
        self.name = name
        self.role = role
        self.phase = "up"  # up | waiting | booting | warming
        self.next_try = 0.0
        self.attempts = 0          # consecutive failed respawns
        self.restarts: list[float] = []  # attempt timestamps (budget window)
        self.died_at: float | None = None
        self.warm_deadline = 0.0
        self.warm_source: int | None = None
        # ts of the last flight record captured for this slot — the dedupe
        # key: repeated on_death calls for one incident (failover + exit
        # sentinel) must not emit the same route.postmortem twice.
        self.postmortem_ts: float | None = None


class Supervisor:
    """Respawn dead replicas, warm them from survivors, admit them back.

    ``spawn(index, name, role) -> ReplicaLink`` is the re-bootstrap
    recipe — for the subprocess tier,
    ``ReplicaProcess.spawn``-with-the-same-worker-argv (``cli/router.py``
    builds it); tests substitute fakes. A spawn that raises (or a
    replacement that dies before admission) counts against the restart
    budget; :data:`~transformer_tpu.serve.resilience.FAULT_POINTS`'s
    ``route.spawn`` fires inside every attempt so crash-loop storms drill
    deterministically.
    """

    def __init__(
        self,
        spawn,
        *,
        max_restarts: int = 3,
        restart_window_s: float = 120.0,
        backoff_ms: float = 200.0,
        backoff_max_ms: float = 10_000.0,
        boot_timeout_s: float = 60.0,
        warm_prefixes: int = 8,
        warm_timeout_s: float = 10.0,
        expected_mesh: "str | None" = None,
        clock=time.monotonic,
    ):
        self._spawn = spawn
        # Sharded-replica shape contract (serve/sharded.py): when the
        # fleet is launched with --mesh, every replica — initial spawn,
        # respawn, scale-up — must come up at this canonical 'data=N'
        # shape. A replacement announcing a DIFFERENT mesh (stale argv,
        # hand-edited recipe, platform that lost devices) is refused
        # loudly at on_ready: killed, counted against the restart budget,
        # and surfaced as a route.mesh_mismatch event — never admitted to
        # serve traffic at the wrong shape.
        self.expected_mesh = expected_mesh
        # Live-weights fix (serve/upgrade.py): a respawn must bootstrap at
        # the fleet's CURRENT target version (Router.weight_target), not
        # the original argv checkpoint — otherwise a heal after a rollout
        # silently resurrects stale weights. Recipes that accept a 4th
        # parameter get the (ckpt_dir, weight_version) target; 3-arg
        # recipes (pre-upgrade fakes and callers) keep working unchanged.
        try:
            self._spawn_takes_target = (
                len(inspect.signature(spawn).parameters) >= 4
            )
        except (TypeError, ValueError):
            self._spawn_takes_target = False
        self.max_restarts = max(1, max_restarts)
        self.restart_window_s = restart_window_s
        self.backoff_ms = backoff_ms
        self.backoff_max_ms = backoff_max_ms
        self.boot_timeout_s = boot_timeout_s
        self.warm_prefixes = warm_prefixes
        self.warm_timeout_s = warm_timeout_s
        self._clock = clock
        self._router = None
        self._slots: dict[int, _SlotState] = {}
        self.stats = {
            "respawns": 0, "spawn_attempts": 0, "spawn_failures": 0,
            "gave_up": 0, "warmed_tokens": 0, "scale_ups": 0, "retired": 0,
            "postmortems": 0,
        }
        self.heal_times: list[float] = []  # death -> admitted, seconds

    # -- wiring (router thread) ---------------------------------------------

    def attach(self, router) -> None:
        self._router = router
        for link in router.links:
            self._slots[link.index] = _SlotState(
                link.index, link.name, link.role
            )

    def _slot(self, index: int) -> _SlotState:
        if index not in self._slots:
            link = self._router.links[index]
            self._slots[index] = _SlotState(index, link.name, link.role)
        return self._slots[index]

    # -- lifecycle events (router thread) -----------------------------------

    def on_death(self, link) -> None:
        """A replica the router just failed over. Schedule a respawn —
        after the breaker cooldown when the PROCESS still runs (the
        half-open revival path gets first claim on a stalled-but-alive
        worker), after the exponential backoff otherwise."""
        if getattr(link, "retired", False):
            return
        slot = self._slot(link.index)
        if slot.phase == "gave_up":
            return  # the budget is spent; only an explicit re-arm respawns
        self._capture_postmortem(slot, link)
        now = self._clock()
        if slot.phase == "up":
            slot.died_at = now
        was_booting = slot.phase in ("booting", "warming")
        slot.phase = "waiting"
        if was_booting:
            # The replacement itself died before admission: a crash-loop
            # signature — count it and back off harder.
            self._count_failure(slot, now)
            if slot.phase == "gave_up":
                return
        delay = self._backoff_s(slot.attempts)
        if link.alive():
            delay = max(
                delay, self._router.breakers[link.index].cooldown_s
            )
        slot.next_try = now + delay

    def _capture_postmortem(self, slot: _SlotState, link) -> None:
        """Salvage the victim's final flight record (obs/flight.py) into a
        ``route.postmortem`` event before the slot is recycled. Two
        origins, freshest first: a record the worker shipped over the wire
        (a ``dump`` reply), else the on-disk autodump next to its
        ``--metrics_jsonl`` — the only trace a SIGKILL leaves. Best-effort
        by contract: no recorder, no file, or a torn dump capture nothing
        and never delay the respawn."""
        record = getattr(link, "flight_record", None)
        origin = "wire"
        if record is None:
            jsonl = getattr(link, "metrics_jsonl", None)
            if jsonl:
                from transformer_tpu.obs.flight import (
                    flight_path_for,
                    load_flight_record,
                )

                record = load_flight_record(flight_path_for(jsonl))
                origin = "file"
        if record is None:
            return
        ts = record.get("ts")
        if ts is not None and ts == slot.postmortem_ts:
            return  # same record already captured for this incident
        slot.postmortem_ts = ts
        self.stats["postmortems"] += 1
        self._router.emit_event(
            "route.postmortem", replica=slot.name, origin=origin,
            reason=record.get("reason"), record=record,
        )

    def _bootstrap(self, index: int, name: str, role: str):
        """One (re)spawn through the deterministic recipe — at the
        fleet's TARGET weight version when a rollout set one, so a
        replacement never serves weights the fleet has moved past."""
        if self._spawn_takes_target:
            return self._spawn(
                index, name, role,
                getattr(self._router, "weight_target", None),
            )
        return self._spawn(index, name, role)

    def _backoff_s(self, attempts: int) -> float:
        return min(
            self.backoff_ms * (2 ** attempts), self.backoff_max_ms
        ) / 1e3

    def _count_failure(self, slot: _SlotState, now: float) -> None:
        slot.attempts += 1
        slot.restarts.append(now)
        self.stats["spawn_failures"] += 1
        self._router.breakers[slot.index].record_failure()
        window = [
            t for t in slot.restarts if now - t <= self.restart_window_s
        ]
        slot.restarts = window
        if len(window) >= self.max_restarts:
            # Crash loop: stop burning CPU. The breaker stays open, the
            # fleet serves at N-1, and the give-up is an explicit event —
            # an operator (or a later manual revive) re-arms the slot.
            slot.phase = "gave_up"
            self.stats["gave_up"] += 1
            self._router.emit_event(
                "route.spawn", replica=slot.name, gave_up=True,
                attempts=len(window),
                window_s=self.restart_window_s,
            )

    # -- the poll loop (router thread, from Router.pump) --------------------

    def poll(self) -> bool:
        """Advance every slot's respawn/warm state machine one turn.
        Returns whether anything progressed (the pump idle signal)."""
        if self._router is None:
            return False
        progressed = False
        now = self._clock()
        for slot in list(self._slots.values()):
            link = self._router.links[slot.index]
            if slot.phase == "waiting" and now >= slot.next_try:
                if not link.dead:
                    # The half-open revival path won while we backed off.
                    slot.phase = "up"
                    slot.attempts = 0
                    continue
                progressed |= self._try_spawn(slot, now)
            elif slot.phase == "booting" and now >= slot.warm_deadline:
                # No ready within the boot timeout: treat as a failed
                # attempt (kill the straggler so the next spawn owns the
                # name cleanly).
                link.kill()
                self._count_failure(slot, now)
                if slot.phase != "gave_up":
                    slot.phase = "waiting"
                    slot.next_try = now + self._backoff_s(slot.attempts)
                progressed = True
            elif slot.phase == "warming" and now >= slot.warm_deadline:
                # Warm-up is best-effort: a slow/dead survivor must not
                # keep a healthy replacement out of the fleet.
                self._admit(link, warmed_tokens=0, timed_out=True)
                progressed = True
        return progressed

    def _try_spawn(self, slot: _SlotState, now: float) -> bool:
        link = self._router.links[slot.index]
        if link.alive():
            # Stalled-but-alive past its cooldown grace and never revived:
            # reclaim the slot before re-bootstrapping.
            link.kill()
        self.stats["spawn_attempts"] += 1
        try:
            maybe_fail("route.spawn")
            new_link = self._bootstrap(slot.index, slot.name, slot.role)
        except Exception:  # noqa: BLE001 — every spawn failure (injected or real: fork limits, a corrupt model spec) is one budgeted attempt, never a crash of the router  # tpa: disable=TPA006
            self._count_failure(slot, now)
            if slot.phase != "gave_up":
                slot.next_try = now + self._backoff_s(slot.attempts)
            return True
        new_link.warming = True
        self._router.replace_link(slot.index, new_link)
        slot.phase = "booting"
        slot.warm_deadline = now + self.boot_timeout_s
        return True

    def on_ready(self, link) -> None:
        """The replacement bootstrapped. Warm its PrefixCache from the
        least-loaded healthy survivor before admitting traffic; with no
        survivor (or no caches), admit cold immediately."""
        slot = self._slot(link.index)
        if slot.phase != "booting":
            return
        if (
            self.expected_mesh is not None
            and getattr(link, "mesh", None) != self.expected_mesh
        ):
            # Wrong-shape refusal: the replacement bootstrapped at a mesh
            # the fleet does not run. Serving it would break the byte-
            # parity contract the shape encodes (and a later export/inject
            # would cross layouts), so refuse BEFORE warm-up or traffic:
            # loud event, kill, one budgeted failure, back off and retry
            # through the deterministic argv.
            now = self._clock()
            self._router.emit_event(
                "route.mesh_mismatch", replica=slot.name,
                expected=self.expected_mesh,
                got=getattr(link, "mesh", None),
            )
            link.kill()
            self._count_failure(slot, now)
            if slot.phase != "gave_up":
                slot.phase = "waiting"
                slot.next_try = now + self._backoff_s(slot.attempts)
            return
        survivor = self._pick_survivor(link.index)
        if survivor is None:
            self._admit(link, warmed_tokens=0)
            return
        try:
            survivor.send({
                "type": "export_state", "limit": self.warm_prefixes,
            })
        except (OSError, ValueError):
            self._admit(link, warmed_tokens=0)
            return
        slot.phase = "warming"
        slot.warm_source = survivor.index
        slot.warm_deadline = self._clock() + self.warm_timeout_s

    def _pick_survivor(self, exclude: int):
        best = None
        for link in self._router.healthy_links:
            if link.index == exclude:
                continue
            if best is None or link.inflight < best.inflight:
                best = link
        return best

    def on_prefix_state(self, from_link, msg: dict) -> None:
        """A survivor answered ``export_state``: forward the payload to
        whichever replacement is warming against it."""
        for slot in self._slots.values():
            if slot.phase != "warming" or slot.warm_source != from_link.index:
                continue
            newbie = self._router.links[slot.index]
            entries = msg.get("entries") or []
            if not entries:
                self._admit(newbie, warmed_tokens=0)
                return
            try:
                newbie.send({"type": "inject_state", "entries": entries})
            except (OSError, ValueError):
                # The replacement died mid-warm; liveness sweep handles it.
                pass
            return

    def on_state_injected(self, link, msg: dict) -> None:
        slot = self._slot(link.index)
        if slot.phase != "warming":
            return
        self._admit(link, warmed_tokens=int(msg.get("tokens", 0)))

    def _admit(self, link, warmed_tokens: int, timed_out: bool = False) -> None:
        """The replacement joins the fleet: rendezvous hashing under its
        old name resumes handing it its affinity keys."""
        slot = self._slot(link.index)
        scale_up = slot.died_at is None  # a FleetScaler spawn, not a heal
        heal_s = None
        if slot.died_at is not None:
            heal_s = self._clock() - slot.died_at
            self.heal_times.append(heal_s)
        slot.phase = "up"
        slot.attempts = 0
        slot.died_at = None
        link.warming = False
        link.dead = False
        link.died_at = None
        # The dead process's breaker state dies with it: the replacement
        # starts CLOSED (an OPEN breaker ignores stray successes by
        # design, so re-arming is this explicit act, never a side effect).
        self._router.reset_breaker(link.index)
        self.stats["respawns"] += 0 if scale_up else 1
        self.stats["warmed_tokens"] += warmed_tokens
        self._router.on_fleet_change()
        self._router.emit_event(
            "route.spawn", replica=slot.name,
            scale_up=scale_up,
            heal_s=None if heal_s is None else round(heal_s, 6),
            warmed_tokens=warmed_tokens,
            warm_timed_out=timed_out or None,
        )

    # -- fleet sizing (FleetScaler / operator surface) ----------------------

    def spawn_new(self, role: str = "both") -> bool:
        """Grow the fleet by one replica (scale-up). The newcomer warms
        like a respawn and joins rendezvous hashing under a fresh name."""
        index = len(self._router.links)
        name = f"replica{index}"
        self.stats["spawn_attempts"] += 1
        try:
            maybe_fail("route.spawn")
            link = self._bootstrap(index, name, role)
        except Exception:  # noqa: BLE001 — a failed scale-up is a skipped decision, not a router crash  # tpa: disable=TPA006
            self.stats["spawn_failures"] += 1
            return False
        link.warming = True
        self._router.append_link(link)
        slot = _SlotState(index, name, role)
        slot.phase = "booting"
        slot.warm_deadline = self._clock() + self.boot_timeout_s
        self._slots[index] = slot
        self.stats["scale_ups"] += 1
        return True

    def retire(self, link) -> None:
        """Begin draining ``link``: the dispatcher stops offering it
        traffic; :meth:`poll`'s sweep ships the shutdown once its
        in-flight work answers (Router.pump calls :meth:`reap_draining`)."""
        link.draining = True

    def reap_draining(self) -> bool:
        progressed = False
        for link in self._router.links:
            if not getattr(link, "draining", False) or link.dead:
                continue
            if link.inflight > 0:
                continue
            try:
                link.send({"type": "shutdown"})
            except (OSError, ValueError):
                pass
            link.draining = False
            link.dead = True
            link.retired = True
            slot = self._slot(link.index)
            slot.phase = "retired"
            self.stats["retired"] += 1
            self._router.on_fleet_change()
            self._router.emit_event("route.retire", replica=link.name)
            progressed = True
        return progressed


class FleetScaler:
    """SLO-burn-driven fleet sizing (the autoscaling policy object).

    Reads the router's live :class:`~transformer_tpu.obs.slo.SLOEngine`
    burn rates — ``signal`` (default ``ttft_p95``) burning > 1 for
    ``sustain_s`` sustained seconds spawns a replica through the
    supervisor (bounded by ``max_replicas``); a fleet idle for ``idle_s``
    (no backlog, no in-flight, burn at 0) retires one (bounded below by
    ``min_replicas``), youngest first so the original rendezvous roster
    is disturbed least. ``cooldown_s`` separates consecutive decisions —
    a burn spike must not double-spawn before its first remedy lands.
    Every decision emits ``route.scale`` with the evidence window (the
    per-window burn rates that justified it) attached.
    """

    def __init__(
        self,
        *,
        signal: str = "ttft_p95",
        sustain_s: float = 5.0,
        idle_s: float = 30.0,
        max_replicas: int = 4,
        min_replicas: int = 1,
        cooldown_s: float = 15.0,
        clock=time.monotonic,
    ):
        self.signal = signal
        self.sustain_s = sustain_s
        self.idle_s = idle_s
        self.max_replicas = max(1, max_replicas)
        self.min_replicas = max(1, min_replicas)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._router = None
        self._sup = None
        self._burn_since: float | None = None
        self._idle_since: float | None = None
        self._last_action = 0.0
        self._last_eval: dict = {}
        self.stats = {"scale_up": 0, "scale_down": 0, "skipped_at_max": 0}

    def bind(self, router, supervisor: Supervisor) -> None:
        self._router = router
        self._sup = supervisor

    def _healthy_count(self) -> int:
        return len(self._router.healthy_links)

    def poll(self, slo_result: "dict | None") -> bool:
        """One scaling turn (router thread, after an SLO evaluation —
        ``slo_result`` is ``SLOEngine.evaluate()``'s payload, or None when
        no evaluation ran this pump)."""
        if self._router is None or self._sup is None:
            return False
        now = self._clock()
        if slo_result is not None:
            self._last_eval = slo_result
        sig = self._last_eval.get(self.signal)
        burn = sig["burn_rate"] if sig else 0.0
        healthy = self._healthy_count()
        # ---- scale up: sustained burn > 1 on the watched signal ----------
        if burn > 1.0:
            self._idle_since = None
            if self._burn_since is None:
                self._burn_since = now
            sustained = now - self._burn_since
            if (
                sustained >= self.sustain_s
                and now - self._last_action >= self.cooldown_s
            ):
                if healthy >= self.max_replicas:
                    self.stats["skipped_at_max"] += 1
                    self._last_action = now  # re-arm, don't spam events
                    return False
                if self._sup.spawn_new():
                    self._last_action = now
                    self.stats["scale_up"] += 1
                    self._router.emit_event(
                        "route.scale", direction="up", signal=self.signal,
                        burn_rate=burn, sustained_s=round(sustained, 3),
                        fleet_size=healthy + 1,
                        evidence=sig.get("windows") if sig else None,
                    )
                    return True
                # A FAILED spawn re-arms the cooldown too: burn is highest
                # exactly when fork/bootstrap is most likely to fail, and
                # falling through would retry at pump frequency — one
                # budgeted attempt per cooldown, like the respawn path.
                self._last_action = now
            return False
        self._burn_since = None
        # ---- scale down: sustained idleness ------------------------------
        idle = (
            self._router.backlog == 0
            and all(l.inflight == 0 for l in self._router.links)
            and burn == 0.0
        )
        if not idle:
            self._idle_since = None
            return False
        if self._idle_since is None:
            self._idle_since = now
            return False
        sustained = now - self._idle_since
        if (
            sustained >= self.idle_s
            and now - self._last_action >= self.cooldown_s
            and healthy > self.min_replicas
        ):
            victim = None
            for link in self._router.healthy_links:  # youngest healthy
                if victim is None or link.index > victim.index:
                    victim = link
            if victim is None:
                return False
            self._sup.retire(victim)
            self._last_action = now
            self._idle_since = None
            self.stats["scale_down"] += 1
            self._router.emit_event(
                "route.scale", direction="down", signal=self.signal,
                burn_rate=burn, sustained_idle_s=round(sustained, 3),
                replica=victim.name, fleet_size=healthy - 1,
                evidence=sig.get("windows") if sig else None,
            )
            return True
        return False
