"""Sharded replicas: one replica = one multi-device pjit program.

``--mesh N`` (or ``data=N``) turns a replica's scheduler into a pjit
program over an N-device serving mesh built by ``parallel/mesh.py``:

- **Params replicate.** ``parallel/sharding.py:state_shardings`` applies
  the partition rules over the serving mesh — whose fsdp/model/expert
  axes are size 1, so every rule resolves to effective replication. This
  is a deliberate layout, not a shortcut: replicated params mean every
  slot's forward is device-local, which is what keeps the decode step
  free of collectives (the Densifying argument: keep the collective set
  small and dense — here, empty) and greedy/sampled answers bit-identical
  across mesh sizes (splitting a float reduction across devices is what
  breaks parity; pure data movement cannot).
- **The KV pool shards on its leading storage axis** — the slot axis for
  the dense layout, the block-row axis for the paged pool — via one
  pytree-prefix ``NamedSharding``. The host-side block-table allocator
  (``kernels/kv_pool.py``) is untouched: tables and indices stay
  replicated host-authoritative arrays, so prefix aliasing, CoW splits,
  spill/restore, and the ``--disaggregate`` wire format work shard-wise
  for free. Cross-shard block traffic (a slot's table row may reference
  blocks resident on any shard) is GSPMD-inserted deterministic data
  movement, bit-exact by construction.
- **The canned jitted programs get explicit in/out shardings** — the
  ``ShardedPrograms`` factory below builds per-scheduler jit twins of the
  module-level programs in ``serve/scheduler.py`` from their unwrapped
  functions, with identical signatures and static/donation structure, so
  every scheduler call site dispatches the twin unchanged. Donated pool
  args carry equal in/out shardings (TPA203's contract), and all call
  sites already pass static args positionally (pjit refuses kwargs once
  in_shardings is given).

This module imports jax lazily so ``serve/replica.py`` can parse
``--mesh`` and grow the virtual CPU platform (``XLA_FLAGS=
--xla_force_host_platform_device_count=N``) BEFORE the first jax import
— the same trick tests/conftest.py and ``analysis/__main__.py`` use.
"""

from __future__ import annotations

# Dense decode/verify at any mesh size must stay collective-free; the
# compiled-HLO gate in analysis/sharding.py (serving_hlo_collectives)
# pins that claim against these exact twins.
_HOT_AXES = ("data", "fsdp", "expert")


def parse_mesh_spec(spec: "str | int | None") -> "int | None":
    """``--mesh`` flag -> serving mesh size. Accepts '' / None (unsharded),
    'N', or 'data=N' (the canonical form heartbeats report). Loud on
    anything else — a silently-misparsed mesh flag would bootstrap a
    replica at the wrong shape, exactly what the supervisor refuses."""
    if spec is None:
        return None
    if isinstance(spec, int):
        n = spec
    else:
        s = str(spec).strip()
        if not s:
            return None
        if s.startswith("data="):
            s = s[len("data="):]
        try:
            n = int(s)
        except ValueError:
            raise ValueError(
                f"--mesh must be '', 'N', or 'data=N', got {spec!r}"
            ) from None
    if n < 1:
        raise ValueError(f"--mesh size must be >= 1, got {n}")
    return n


def normalize_mesh_spec(spec: "str | int | None") -> "str | None":
    """Canonical mesh-shape string ('data=N') — the ONE rendering the
    replica's ready/heartbeat messages report and the supervisor's
    ``expected_mesh`` compares against, so flag spellings ('2' vs
    'data=2') can never alias into a false mismatch."""
    n = parse_mesh_spec(spec)
    return None if n is None else f"data={n}"


def serving_mesh(n: int):
    """The N-device serving mesh: ``MeshConfig(data=N)`` over the first N
    local devices. All other axes are size 1, so the partition rules
    resolve to replication and the batch axes ('data', 'fsdp', 'expert')
    collapse onto 'data' — see the module docstring for why."""
    import jax

    from transformer_tpu.config import MeshConfig
    from transformer_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"mesh data={n} needs {n} devices, platform has {len(devices)} "
            f"({devices[0].platform}). On CPU, grow the virtual platform "
            "with XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{n} before jax initializes (serve/replica.py --mesh does "
            "this automatically in its own process)."
        )
    return make_mesh(MeshConfig(data=n), devices[:n])


class ShardedPrograms:
    """jit twins of the scheduler's canned programs with explicit in/out
    shardings over a serving mesh. Attribute names mirror the module
    programs minus the leading underscore; signatures, static argnames,
    and donation structure are identical, so ``ContinuousScheduler``
    swaps them in via its ``_fn_*`` dispatch with zero call-site churn.
    """

    def __init__(self, mesh, params):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from transformer_tpu.parallel.sharding import state_shardings
        from transformer_tpu.serve import scheduler as smod

        self.mesh = mesh
        axes = tuple(a for a in _HOT_AXES if a in mesh.shape)
        # One pytree-prefix sharding for the whole pool: every leaf of
        # both KV layouts carries the sharded storage axis LEADING (dense:
        # stacked slots + the (N,) index; paged: block-pool rows), which
        # is what lets one prefix cover k/v/scale leaves of every cache
        # variant (bf16/int8/GQA) without per-leaf rules.
        self.pool = NamedSharding(mesh, P(axes))
        self.repl = NamedSharding(mesh, P())
        self.params = state_shardings(params, mesh)
        PS, L, R = self.params, self.pool, self.repl

        def twin(fn, *, statics=(), donate=(), ins, outs):
            return jax.jit(
                fn.__wrapped__ if hasattr(fn, "__wrapped__") else fn,
                static_argnames=statics, donate_argnums=donate,
                in_shardings=ins, out_shardings=outs,
            )

        # ---- dense layout -------------------------------------------------
        self.pool_step = twin(
            smod._pool_step, statics=("cfg",), donate=(1,),
            ins=(PS, L, L), outs=(L, L),
        )
        self.pool_verify = twin(
            smod._pool_verify, statics=("cfg",), donate=(1,),
            ins=(PS, L, L), outs=(L, L),
        )
        self.pool_rollback = twin(
            smod._pool_rollback, donate=(0,), ins=(L, L), outs=L,
        )
        self.slot_prefill = twin(
            smod._slot_prefill, statics=("cfg", "chunk"),
            ins=(PS, L, R, R, R), outs=(R, L),
        )
        self.slot_restore = twin(
            smod._slot_restore, ins=(L, R, R), outs=L,
        )
        self.slot_read_blocks = twin(
            smod._slot_read_blocks, statics=("n",), ins=(L, R, R), outs=R,
        )
        # ---- paged layout -------------------------------------------------
        # Tables/indices stay replicated (host-authoritative, a few KB);
        # the pool's block rows shard. paged_flash has no twin: the fused
        # Pallas kernels are single-device programs by construction, and
        # the scheduler refuses that combination at build time.
        self.pool_step_paged = twin(
            smod._pool_step_paged,
            statics=("cfg", "block_tokens", "buf_len"), donate=(1,),
            ins=(PS, L, R, R, R), outs=(R, L),
        )
        self.pool_verify_paged = twin(
            smod._pool_verify_paged,
            statics=("cfg", "block_tokens", "buf_len"), donate=(1,),
            ins=(PS, L, R, R, R), outs=(R, L),
        )
        self.slot_prefill_paged = twin(
            smod._slot_prefill_paged,
            statics=("cfg", "chunk", "block_tokens", "buf_len"),
            ins=(PS, L, R, R, R, R), outs=(R, L),
        )
        self.pool_write_blocks = twin(
            smod._pool_write_blocks, ins=(L, R, R), outs=L,
        )
        self.pool_read_block = twin(
            smod._pool_read_block, ins=(L, R), outs=R,
        )
        self.pool_copy_blocks = twin(
            smod._pool_copy_blocks, ins=(L, R, R), outs=L,
        )

    def place_params(self, params):
        """Commit a param pytree to its partition-rule shardings (no-op
        bytes-wise on the serving mesh — the rules replicate — but the
        commitment is what makes every later dispatch resharding-free)."""
        import jax

        return jax.device_put(params, self.params)

    def place_pool(self, caches):
        """Commit pool KV storage to the leading-axis shard."""
        import jax

        return jax.device_put(caches, self.pool)

    def check_staged_shardings(self, staged) -> list:
        """The staged-params twin check grown to sharding specs: leaves
        already committed to a device layout must agree with the serving
        mesh's partition rules — a staged pytree living on a DIFFERENT
        mesh (wrong device set or wrong spec) would make the swap reshard
        or crash mid-flight. Host arrays (the checkpoint-load case) pass:
        ``place_params`` commits them. Returns human-readable mismatch
        strings, empty when clean."""
        import jax

        flat_want = jax.tree_util.tree_flatten_with_path(self.params)[0]
        flat_got = jax.tree_util.tree_flatten_with_path(staged)[0]
        bad = []
        for (path, want), (_, leaf) in zip(flat_want, flat_got):
            got = getattr(leaf, "sharding", None)
            if got is None or not isinstance(leaf, jax.Array):
                continue  # host array: placed at stage time
            if getattr(leaf, "committed", True) and not got.is_equivalent_to(
                want, getattr(leaf, "ndim", 0)
            ):
                key = "/".join(str(getattr(p, "key", p)) for p in path)
                bad.append(f"{key}: staged on {got} != serving {want}")
        return bad
