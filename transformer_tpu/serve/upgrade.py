"""Live-weights control plane: zero-downtime rolling checkpoint swaps.

Production serving cannot restart to pick up a new checkpoint — and after
PR 11 a restart is exactly what a respawn IS, so an out-of-band weight
change would be silently reverted by the next heal. This module makes
weight rollout a first-class *control-plane* operation over the PR 10/11
fleet (docs/SERVING.md "Live-weights rollout"):

- **Verified integrity at the door**: :func:`verify_checkpoint` checks the
  target checkpoint's per-array crc32 manifest (``train/checkpoint.py``)
  BEFORE any replica is touched — a torn, bit-rotted, or mixed checkpoint
  is rejected fleet-wide with a structured ``upgrade`` error and zero
  impact on serving. The manifest digest doubles as the rollout's
  ``weight_version`` tag. Replicas re-verify (and structure-check against
  their RUNNING params) before anything is staged —
  :func:`load_checkpoint_params`.
- **Rolling, router-coordinated swap** (:class:`UpgradeCoordinator`, owned
  by the router thread like the Supervisor): one replica at a time is
  *quiesced* through the existing dispatch policy (``link.upgrading`` —
  no new dispatches; in-flight requests finish on their admission-time
  weights), told to stage the verified params (the scheduler's two-version
  param slot flips at a drained step boundary with **zero recompiles** —
  the staged tree is a structure/shape/dtype twin), then re-admitted.
- **Canary gating**: the FIRST upgraded replica is the canary. The router
  pins a deterministic traffic slice to it (every ``canary_every``-th
  accepted order), and a per-``weight_version`` split of the PR 9
  :class:`~transformer_tpu.obs.slo.SLOEngine` evaluates the canary's burn
  (availability / ttft_p95 over short windows). Sustained burn > 1 rolls
  the canary BACK — the old params are still the resident second buffer,
  so rollback is an O(1) re-stage — and the rollout ends with
  ``route.upgrade rolled_back=true`` carrying the burn evidence. A clean
  window promotes the rollout to the rest of the fleet.
- **Respawn at the fleet's target version**: a successful rollout sets
  ``Router.weight_target``; the supervisor's spawn recipe appends
  ``--init_ckpt``/``--weight_version`` so a replica killed mid- or
  post-rollout is re-bootstrapped at the version the fleet is CONVERGING
  TO, not the argv checkpoint it was originally launched with (the
  stale-respawn bug this PR fixes). A rollback clears the target.

Fault plane (docs/ROBUSTNESS.md): ``route.upgrade`` fires inside the
coordinator's per-replica swap dispatch (an injected fault aborts the
rollout and rolls upgraded replicas back), ``route.canary`` marks canary
answers bad (deterministic burn → rollback drills), and ``ckpt.swap``
fires inside the scheduler's step-boundary flip (the swap aborts with the
old weights still serving).

Threading contract (TPA101-105): every method runs on the ROUTER thread
(``Router.pump`` drives :meth:`UpgradeCoordinator.poll`; ``observe``/
``on_msg``/``on_death`` are called from the router's inbox drain and
answer funnel). The checkpoint helpers at the top are host-side
numpy/stdlib; :func:`load_checkpoint_params` (replica side) is the only
function that touches jax, and only lazily.
"""

from __future__ import annotations

import os
import time

from transformer_tpu.serve.resilience import fired, maybe_fail

#: ``route_upgrade_state`` gauge values (obs; docs/OBSERVABILITY.md).
UPGRADE_STATE_VALUE = {
    "idle": 0, "quiesce": 1, "swap": 2, "canary": 3, "rolling": 4,
    "rolling_back": 5, "rolled_back": 6, "done": 7, "failed": 8,
}


class UpgradeError(RuntimeError):
    """A checkpoint failed verification or structure-matching — the
    rollout (or replica load) refuses it before any swap is scheduled."""


def resolve_checkpoint_dir(path: str) -> str:
    """Accept either one checkpoint directory (holding ``arrays.npz``) or
    a CheckpointManager directory (pick the newest ``ckpt_*`` step)."""
    if os.path.exists(os.path.join(path, "arrays.npz")):
        return path
    if os.path.isdir(path):
        import re

        steps = sorted(
            name for name in os.listdir(path)
            if re.fullmatch(r"ckpt_\d{8}", name)
        )
        if steps:
            return os.path.join(path, steps[-1])
    raise UpgradeError(
        f"no checkpoint at {path!r}: expected arrays.npz or ckpt_* steps"
    )


def verify_checkpoint(path: str) -> "tuple[str, str]":
    """Fleet-wide admission check for an upgrade target: resolve the
    checkpoint dir and byte-verify it against its manifest. Returns
    ``(ckpt_dir, weight_version digest)``; raises :class:`UpgradeError`
    with the integrity failure (torn manifest, crc mismatch, missing
    manifest — an unmanifested checkpoint cannot prove byte-consistency
    across N replicas, so the control plane refuses it)."""
    ckpt_dir = resolve_checkpoint_dir(path)
    from transformer_tpu.train.checkpoint import verify_manifest

    try:
        return ckpt_dir, verify_manifest(ckpt_dir)
    except UpgradeError:
        raise
    except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — admission check: EVERY failure shape (torn manifest, truncated npz, missing file, crc mismatch) must become one structured refusal with serving untouched, never a router crash
        raise UpgradeError(
            f"checkpoint at {ckpt_dir} failed integrity verification: "
            f"{type(e).__name__}: {e}"
        ) from e


def load_checkpoint_params(path: str, template) -> "tuple[object, str]":
    """Replica-side verified load: byte-verify the checkpoint, then check
    its arrays against the RUNNING param tree — same key set, same
    per-leaf shapes AND dtypes (a swap must re-run the compiled programs,
    so nothing may differ but values). Returns ``(params, digest)`` with
    the params rebuilt in ``template``'s tree structure; raises
    :class:`UpgradeError` on any mismatch, before anything is staged."""
    import jax
    import numpy as np

    from transformer_tpu.train.checkpoint import (
        _SEP,
        _path_elem,
        verify_manifest,
    )

    ckpt_dir = resolve_checkpoint_dir(path)
    with np.load(os.path.join(ckpt_dir, "arrays.npz")) as data:
        flat = {k: data[k] for k in data.files}
    try:
        version = verify_manifest(ckpt_dir, flat)
    except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — same admission-check contract as verify_checkpoint: one structured refusal, serving untouched
        raise UpgradeError(
            f"checkpoint at {ckpt_dir} failed integrity verification: "
            f"{type(e).__name__}: {e}"
        ) from e
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    problems = []
    for p, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(e) for e in p)
        if key not in flat:
            problems.append(f"missing {key!r}")
            continue
        arr = flat[key]
        ref = np.asarray(leaf)
        if arr.shape != ref.shape or arr.dtype != ref.dtype:
            problems.append(
                f"{key}: checkpoint {arr.shape}/{arr.dtype} != running "
                f"{ref.shape}/{ref.dtype}"
            )
            continue
        new_leaves.append(arr)
    extra = sorted(set(flat) - {
        _SEP.join(_path_elem(e) for e in p) for p, _ in leaves_with_path
    })
    if extra:
        problems.append(f"{len(extra)} extra array(s), e.g. {extra[0]!r}")
    if problems:
        raise UpgradeError(
            f"checkpoint {version} at {ckpt_dir} does not match the running "
            f"model spec ({'; '.join(problems[:3])}) — swap refused"
        )
    return jax.tree_util.tree_unflatten(treedef, new_leaves), version


def _default_canary_slos():
    """Short-window availability + TTFT objectives for the canary verdict
    — deliberately tighter windows than the serving defaults (a canary
    window is seconds, not hours)."""
    from transformer_tpu.obs.slo import SLOSpec

    return (
        SLOSpec("availability", "availability", 0.99, windows=(5.0, 30.0)),
        SLOSpec(
            "ttft_p95", "ttft_p95", 0.95, threshold_s=2.0,
            windows=(5.0, 30.0),
        ),
    )


class UpgradeCoordinator:
    """Router-thread rollout state machine (see the module docstring).

    ``verify`` (injectable for the deterministic-schedule scenario and
    fakes) maps an upgrade path to ``(ckpt_dir, weight_version)`` —
    default :func:`verify_checkpoint`. ``canary_slos`` is an
    ``--slo_spec``-grammar string or a spec tuple for the per-version
    burn split; ``canary_every`` pins every N-th accepted order to the
    canary (0 = the fleet size at rollout start, so the canary keeps its
    fair deterministic share)."""

    def __init__(
        self,
        *,
        canary_window_s: float = 5.0,
        canary_min_requests: int = 4,
        canary_every: int = 0,
        canary_slos=None,
        quiesce_timeout_s: float = 60.0,
        swap_timeout_s: float = 60.0,
        verify=None,
        clock=time.monotonic,
    ):
        self.canary_window_s = canary_window_s
        self.canary_min_requests = max(1, canary_min_requests)
        self._canary_every_cfg = canary_every
        if canary_slos is None:
            self._canary_specs = _default_canary_slos()
        elif isinstance(canary_slos, str):
            from transformer_tpu.obs.slo import parse_slo_spec

            self._canary_specs = parse_slo_spec(canary_slos)
        else:
            self._canary_specs = tuple(canary_slos)
        self.quiesce_timeout_s = quiesce_timeout_s
        self.swap_timeout_s = swap_timeout_s
        self._verify = verify if verify is not None else verify_checkpoint
        self._clock = clock
        self._router = None
        self.state = "idle"
        # Rollout-scoped state (reset by start()).
        self._ckpt: str | None = None
        self.target_version: str | None = None
        self._queue: list[int] = []          # replica indices still to do
        self._current: int | None = None     # index being quiesced/swapped
        self._phase_t0 = 0.0
        self._quiesce_t0 = 0.0
        self._started_at = 0.0
        self._canary: int | None = None
        self._canary_every = 2
        self._canary_t0 = 0.0
        self._canary_seen = 0
        self._promoted = False
        self._rolling_back: set[int] = set()
        self._rollback_reason: str | None = None
        self._engines: dict = {}             # weight_version -> SLOEngine
        self.stats = {
            "started": 0, "completed": 0, "rejected": 0, "rollbacks": 0,
            "aborted": 0, "replicas_upgraded": 0, "canary_requests": 0,
            "injected_canary_burn": 0,
        }

    # -- wiring (router thread) ---------------------------------------------

    def attach(self, router) -> None:
        self._router = router

    def _set_state(self, state: str) -> None:
        self.state = state
        router = self._router
        if router is not None and router._tel is not None:
            router._tel.registry.gauge(
                "route_upgrade_state",
                "rollout state: 0 idle, 1 quiesce, 2 swap, 3 canary, "
                "4 rolling, 5 rolling_back, 6 rolled_back, 7 done, 8 failed",
            ).set(UPGRADE_STATE_VALUE[state])

    def _emit(self, kind: str, **fields) -> None:
        self._router.emit_event(kind, **fields)

    # -- rollout admission ----------------------------------------------------

    @property
    def active(self) -> bool:
        return self.state in (
            "quiesce", "swap", "canary", "rolling", "rolling_back"
        )

    def start(self, path: str) -> dict:
        """Begin a rollout to the checkpoint at ``path``. Integrity is
        enforced HERE, fleet-wide, before any replica is touched: a torn
        or mismatched checkpoint answers a structured ``upgrade`` refusal
        and serving is not disturbed. Returns a status dict (the control
        line answers it verbatim)."""
        if self._router is None:
            return {"ok": False, "code": "upgrade",
                    "error": "no router attached"}
        if self.active:
            return {
                "ok": False, "code": "upgrade",
                "error": f"a rollout to {self.target_version} is already "
                         f"in flight (state {self.state})",
            }
        try:
            ckpt_dir, version = self._verify(path)
        except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — rejection IS the feature: any verification failure becomes one structured refusal event with zero serving impact
            self.stats["rejected"] += 1
            self._emit(
                "route.upgrade", phase="rejected", ckpt=path,
                error=f"{type(e).__name__}: {e}",
            )
            return {"ok": False, "code": "upgrade",
                    "error": f"{type(e).__name__}: {e}"}
        roster = [
            l.index for l in self._router.links
            if not l.dead and not l.retired and l.wv != version
        ]
        if not roster:
            self.stats["rejected"] += 1
            self._emit(
                "route.upgrade", phase="rejected", ckpt=ckpt_dir,
                version=version,
                error="no live replica needs this version",
            )
            return {"ok": False, "code": "upgrade", "version": version,
                    "error": "no live replica needs this version"}
        self._ckpt = ckpt_dir
        self.target_version = version
        self._queue = roster
        self._current = None
        self._canary = None
        self._canary_seen = 0
        self._promoted = False
        self._rolling_back = set()
        self._rollback_reason = None
        self._engines = {}
        # The documented default slice: 1/fleet-size (the canary's fair
        # share of the LIVE fleet, not of the not-yet-converged roster).
        fleet = sum(
            1 for l in self._router.links if not l.dead and not l.retired
        )
        self._canary_every = self._canary_every_cfg or max(2, fleet)
        self._started_at = self._clock()
        self.stats["started"] += 1
        # Respawns from here on come up at the TARGET version: a replica
        # SIGKILLed mid-rollout must not resurrect the stale argv weights.
        self._router.weight_target = (ckpt_dir, version)
        self._set_state("quiesce")
        self._emit(
            "route.upgrade", phase="started", ckpt=ckpt_dir,
            version=version, canary_every=self._canary_every,
            replicas=[self._router.links[i].name for i in roster],
        )
        return {"ok": True, "version": version, "replicas": len(roster)}

    # -- the poll loop (router thread, from Router.pump) ----------------------

    def poll(self) -> bool:
        if self._router is None or not self.active:
            return False
        now = self._clock()
        if self.state == "rolling_back":
            return self._poll_rollback(now)
        if self.state == "canary":
            return self._poll_canary(now)
        # quiesce / swap / rolling: drive the current replica forward.
        if self._current is None:
            return self._pick_next(now)
        link = self._router.links[self._current]
        if link.dead:
            # Mid-swap death: failover already re-queued its work; the
            # supervisor respawns it AT THE TARGET VERSION (weight_target
            # is set), so this index needs no further coordination —
            # continue the rollout with the rest.
            link.upgrading = False
            self._current = None
            return True
        if self.state == "quiesce":
            if link.inflight == 0:
                return self._send_swap(link, now)
            if now - self._quiesce_t0 > self.quiesce_timeout_s:
                self._abort(
                    f"replica {link.name} did not drain within "
                    f"{self.quiesce_timeout_s:g}s"
                )
                return True
            return False
        if self.state == "swap" and now - self._phase_t0 > self.swap_timeout_s:
            self._abort(
                f"replica {link.name} did not confirm the swap within "
                f"{self.swap_timeout_s:g}s"
            )
            return True
        return False

    def _pick_next(self, now: float) -> bool:
        while self._queue:
            index = self._queue.pop(0)
            link = self._router.links[index]
            if link.dead or link.retired or link.wv == self.target_version:
                # Dead replicas respawn at the target; already-converged
                # ones (a respawn that beat us here) need nothing.
                continue
            self._current = index
            link.upgrading = True
            self._quiesce_t0 = now
            self._set_state("quiesce")
            return True
        self._complete(now)
        return True

    def _send_swap(self, link, now: float) -> bool:
        try:
            # route.upgrade fault point: a deterministically injected
            # dispatch failure aborts the rollout (and rolls upgraded
            # replicas back) — the mid-rollout-abort drill.
            maybe_fail("route.upgrade")
            link.send({
                "type": "upgrade", "ckpt": self._ckpt,
                "version": self.target_version,
            })
        except (OSError, ValueError) as e:
            self._abort(
                f"upgrade dispatch to {link.name} failed: "
                f"{type(e).__name__}: {e}"
            )
            return True
        self._phase_t0 = now
        self._set_state("swap")
        return True

    def _complete(self, now: float) -> None:
        self._current = None
        self.stats["completed"] += 1
        self._set_state("done")
        # weight_target STAYS set: future respawns and scale-ups bootstrap
        # at the fleet's converged version (the stale-respawn fix).
        self._emit(
            "route.upgrade", phase="completed",
            version=self.target_version,
            time_to_upgrade_s=round(now - self._started_at, 6),
            replicas_upgraded=self.stats["replicas_upgraded"],
        )

    # -- canary ---------------------------------------------------------------

    def _canary_engine(self, version: str):
        if version not in self._engines:
            from transformer_tpu.obs.slo import SLOEngine

            self._engines[version] = SLOEngine(
                self._canary_specs, interval=0.0, clock=time.time,
            )
        return self._engines[version]

    def _poll_canary(self, now: float) -> bool:
        link = self._router.links[self._canary]
        if link.dead:
            # A dead canary is NOT a clean window. Its replacement
            # (respawning at the target version) inherits the slice and
            # on_death restarted the window — but a canary that STAYS
            # dead (the new weights crash it, the respawn budget
            # exhausts) must read as a rollback signal, never as
            # traffic-starved promotion: burn stays 0 exactly because
            # failovers answered on old-version survivors.
            if now - self._canary_t0 >= 4 * max(self.canary_window_s, 0.5):
                self._begin_rollback(
                    "canary replica died on the new weights and did not "
                    "recover"
                )
                return True
            return False
        result = self._canary_engine(self.target_version).maybe_evaluate()
        breached = [
            name for name, r in (result or {}).items() if r["breached"]
        ]
        if breached:
            evidence = {
                name: {
                    k: w["burn_rate"]
                    for k, w in (result or {})[name]["windows"].items()
                }
                for name in breached
            }
            self._begin_rollback(
                f"canary burn > 1 sustained on {'+'.join(breached)}",
                evidence=evidence,
            )
            return True
        elapsed = now - self._canary_t0
        if elapsed >= self.canary_window_s and (
            self._canary_seen >= self.canary_min_requests
            or elapsed >= 4 * self.canary_window_s
        ):
            # Clean window: promote the rollout to the rest of the fleet.
            # (4x the window with too-little traffic promotes too — an
            # idle fleet must not wedge its own upgrade forever.)
            self._promoted = True
            self._emit(
                "route.canary", phase="promoted",
                replica=self._router.links[self._canary].name,
                version=self.target_version,
                window_s=round(elapsed, 3), requests=self._canary_seen,
            )
            self._set_state("rolling")
            return True
        return False

    def route(self, rr, usable):
        """The router's canary pin: during the canary window, every
        ``canary_every``-th accepted order routes to the canary (when it
        can serve the stage) — a deterministic slice, so the drill and
        the share number replay exactly."""
        if self.state != "canary" or self._canary is None:
            return None
        if rr.order % self._canary_every != 0:
            return None
        link = self._router.links[self._canary]
        return link if link in usable else None

    def observe(self, rr, resp: dict, slo) -> None:
        """Answer-funnel tap (router thread): split every tagged answer
        into its weight_version's SLO engine — the per-version burn the
        canary verdict reads. The ``route.canary`` fault point marks
        canary answers bad here, so burn-triggered rollback is a
        deterministic ``--fault_spec`` drill."""
        if not self.active or self.target_version is None:
            return
        version = resp.get("weight_version")
        if version is None:
            return
        sample = dict(slo) if isinstance(slo, dict) else {}
        sample.setdefault("total_s", 0.0)
        if "error" in resp:
            sample["error"] = resp["error"]
            if "code" in resp:
                sample["code"] = resp["code"]
        if version == self.target_version:
            if self.state == "canary":
                self._canary_seen += 1
                self.stats["canary_requests"] += 1
            if fired("route.canary"):
                # Injected canary burn: the sample is recorded as an
                # availability failure (and a TTFT bust when it carried a
                # latency), so the rollback ladder drills end-to-end.
                self.stats["injected_canary_burn"] += 1
                sample["error"] = "injected canary burn (route.canary)"
                sample["ttft_s"] = 1e9
        self._canary_engine(version).record(sample)

    # -- replica messages (router inbox, router thread) ----------------------

    def on_msg(self, link, msg: dict) -> None:
        kind = msg.get("type")
        if kind == "upgrade_staged":
            if not msg.get("ok"):
                # The replica refused the checkpoint (digest/structure
                # mismatch, torn file): reject fleet-wide.
                self._abort(
                    f"replica {link.name} refused the checkpoint: "
                    f"{msg.get('error')}"
                )
            return
        if kind != "upgraded":
            return
        version = msg.get("version")
        if not msg.get("ok", True):
            if self._rolling_back or self.state == "rolling_back":
                # A rollback swap failing (ckpt.swap firing twice) leaves
                # the replica on the NEW weights; note it and move on —
                # the operator sees the failed state and the versions.
                self._rolling_back.discard(link.index)
                return
            if not self.active:
                return  # a stale abort outside any rollout
            self._abort(
                f"replica {link.name} swap aborted: {msg.get('error')}"
            )
            return
        link.wv = version
        if self._rolling_back or self.state in (
            "rolling_back", "rolled_back", "failed"
        ):
            if (
                version == self.target_version
                and self.target_version is not None
                and not link.dead
            ):
                # The quiesced swap landed AFTER the rollback decision
                # (a late confirmation that raced the abort): converge
                # this replica back too — a half-upgraded fleet is the
                # one state the control plane must never leave behind.
                try:
                    link.upgrading = True
                    link.send({"type": "rollback"})
                    self._rolling_back.add(link.index)
                    if self.state in ("rolled_back", "failed"):
                        self._set_state("rolling_back")
                except (OSError, ValueError):
                    link.upgrading = False  # failover handles it
            else:
                self._rolling_back.discard(link.index)
                link.upgrading = False
            return
        if not self.active or version != self.target_version:
            return  # a stale/rollback confirmation outside a rollout
        link.upgrading = False
        self.stats["replicas_upgraded"] += 1
        now = self._clock()
        self._emit(
            "route.upgrade", phase="swapped", replica=link.name,
            version=version,
            quiesce_s=round(self._phase_t0 - self._quiesce_t0, 6),
            swap_s=round(now - self._phase_t0, 6),
        )
        if self._current == link.index:
            self._current = None
        if self._canary is None and not self._promoted:
            # First upgraded replica = the canary: pin its slice, start
            # the window, and HOLD the rollout until the verdict.
            self._canary = link.index
            self._canary_t0 = now
            self._canary_seen = 0
            self._set_state("canary")
            self._emit(
                "route.canary", phase="started", replica=link.name,
                version=version, every=self._canary_every,
                window_s=self.canary_window_s,
            )
        else:
            self._set_state("rolling")

    def on_death(self, link) -> None:
        """Router failover notification: a mid-rollout death needs no
        special handling beyond un-pinning — the supervisor respawns the
        index at ``weight_target``, and the roster skip in
        ``_pick_next``/``poll`` treats the replacement as converged."""
        if not self.active:
            return
        link.upgrading = False
        if self._current == link.index:
            self._current = None
        if self.state == "canary" and self._canary == link.index:
            # The canary died mid-window: its REPLACEMENT (same index,
            # target version) inherits the slice; restart the window so
            # the verdict covers only replacement traffic.
            self._canary_t0 = self._clock()
            self._canary_seen = 0
        if link.index in self._rolling_back:
            self._rolling_back.discard(link.index)

    # -- rollback / abort -----------------------------------------------------

    def _begin_rollback(self, reason: str, evidence=None) -> None:
        """Swap every already-upgraded replica BACK to the resident old
        params (they are still the second buffer — an O(1) re-stage) and
        surrender the rollout. The canary-burn path and the mid-rollout
        abort path both land here."""
        self.stats["rollbacks"] += 1
        self._rollback_reason = reason
        self._rolling_back = set()
        self._queue = []  # a surrendered rollout must never resume
        router = self._router
        router.weight_target = None  # respawns revert to argv weights
        for link in router.links:
            if link.dead or link.retired:
                continue
            if link.wv == self.target_version:
                link.upgrading = True  # quiesce for the rollback swap too
                try:
                    link.send({"type": "rollback"})
                    self._rolling_back.add(link.index)
                except (OSError, ValueError):
                    link.upgrading = False  # failover will handle it
            else:
                link.upgrading = False
        self._current = None
        self._set_state("rolling_back")
        self._emit(
            "route.upgrade", phase="rolled_back", rolled_back=True,
            version=self.target_version, reason=reason,
            evidence=evidence,
            replicas=[
                router.links[i].name for i in sorted(self._rolling_back)
            ],
        )

    def _poll_rollback(self, now: float) -> bool:
        self._rolling_back = {
            i for i in self._rolling_back
            if not self._router.links[i].dead
            and self._router.links[i].wv == self.target_version
        }
        if self._rolling_back:
            return False
        self._set_state(
            "rolled_back" if self._rollback_reason else "failed"
        )
        return True

    def _abort(self, reason: str) -> None:
        """A structural failure (refused checkpoint, swap fault, dispatch
        failure, drain timeout): emit the evidence and converge the fleet
        BACK to the old version — a half-upgraded fleet is the one state
        the control plane must never leave behind."""
        self.stats["aborted"] += 1
        self._emit(
            "route.upgrade", phase="failed", version=self.target_version,
            error=reason,
        )
        self._begin_rollback(reason)
        self._rollback_reason = None  # final state "failed", not rolled_back
