"""Fault-tolerant serving: deterministic fault injection + circuit breakers.

At the scale the ROADMAP targets (multi-replica serving, millions of
users), partial failure is the steady state — the Mesh-TensorFlow premise
(PAPERS.md) applied to the serving tier: a drafter that hangs, a disk that
fills under the event log, a flipped bit in a cached KV block. PR 6 gave
the tier a machine-checked answer to "what happens when threads interleave
badly"; this module gives it one for "what happens when X breaks
mid-request", in three parts (docs/ROBUSTNESS.md is the long-form
catalogue):

- **Fault plane** (:class:`FaultPlane`): named, seeded injection points
  threaded through the scheduler (``serve.prefill``), the prefix cache
  (``prefix.match`` / ``prefix.corrupt`` / ``prefix.insert``), the
  speculative drafters (``draft.propose`` / ``draft.slow``), the telemetry
  sink (``obs.emit``), checkpoint commits (``ckpt.write``) and the data
  prefetch thread (``data.prefetch``). Enabled via ``--fault_spec`` or the
  test API (:func:`active`); a disabled plane costs ONE module-global
  ``None`` check per site and adds nothing to any trace (the
  ``fault_plane_inert`` contract pins jaxpr byte-identity, like
  telemetry).
- **Deterministic schedules**: every rule fires as a pure function of
  ``(seed, point, call_index)`` — the same spec replays the same fault
  episode, so a chaos failure is a reproducible test case, not a flake.
- **Circuit breakers** (:class:`CircuitBreaker`): K consecutive faults
  fail a subsystem OPEN to the plain byte-parity path (speculation stops
  drafting, the prefix cache stops matching/feeding, the event sink goes
  quiet), a cooldown later one HALF-OPEN probe decides recovery. Breaker
  state exports as obs gauges + ``serve.breaker`` events; ``obs
  summarize`` reports degraded time.

Import contract: stdlib-only (no jax, no numpy). Serve-side modules import
this directly; jax-free leaves (``obs/events.py``) and heavyweight-import
leaves (``train/checkpoint.py``, ``data/pipeline.py``) instead expose a
module-level ``fault_hook`` attribute that :func:`install` fills in — the
dependency points INTO this module only from code that already lives in
``serve/``.

Injected faults subclass ``OSError`` on purpose: at leaf sites (event-log
writes, checkpoint renames, prefetch ``device_put``) the injection flows
through exactly the ``except (OSError, ...)`` handler a real environmental
failure would take — the chaos suite exercises the production handlers,
not parallel test-only ones. They also subclass :class:`TransientError`,
the marker the scheduler's bounded admission retry keys on.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time
from typing import Iterator

# The breaker primitive lives in obs/ (stdlib-only, importable by the CLI
# flag layer without the serve stack — the event-log sink is itself a
# protected subsystem); re-exported here as part of the resilience surface.
from transformer_tpu.obs.breaker import (
    BREAKER_STATE_VALUE,
    CircuitBreaker,
)

#: Every injection point the plane recognizes — a typo'd ``--fault_spec``
#: fails at parse time, not silently never-fires. docs/ROBUSTNESS.md holds
#: the per-point semantics table.
FAULT_POINTS = frozenset({
    "serve.prefill",    # raise inside slot admission, before the prefill pick
    "prefix.match",     # raise inside PrefixCache.match (trie walk)
    "prefix.corrupt",   # flip a byte of a matched KV block (checksum catches)
    "prefix.insert",    # raise inside PrefixCache.insert (retirement feed)
    "draft.propose",    # raise inside the drafter's propose
    "draft.slow",       # sleep inside the drafter's propose (ms=N)
    "obs.emit",         # raise inside EventLog.emit's write
    "ckpt.write",       # raise inside CheckpointManager._commit (pre-rename)
    "data.prefetch",    # raise inside the prefetch worker, before device_put
    # Router-tier points (the supervision/HA drill surface, PR 11):
    "route.spawn",      # raise inside the supervisor's replica (re)spawn —
    #                     a crash-looping bootstrap, deterministically
    "route.hb",         # swallow a replica heartbeat at the router —
    #                     heartbeat-loss/failover storms without real stalls
    "route.takeover",   # raise inside the standby's per-replica takeover
    #                     handshake — partial adoptions + split-brain drills
    # Live-weights control plane (serve/upgrade.py, PR 15):
    "ckpt.swap",        # raise inside the scheduler's step-boundary param
    #                     flip — the swap aborts with old weights serving
    "route.upgrade",    # raise inside the coordinator's per-replica swap
    #                     dispatch — mid-rollout aborts + fleet rollback
    "route.canary",     # mark a canary answer bad in the per-version SLO
    #                     split — deterministic burn -> auto-rollback drills
})


class TransientError(RuntimeError):
    """Marker for failures worth a bounded, jitter-backed admission retry
    (as opposed to validation errors, which retrying can never fix)."""


class InjectedFault(OSError, TransientError):
    """A fault the plane fired. Subclasses ``OSError`` so leaf sites catch
    it exactly where they catch the real environmental failure it stands
    in for, and :class:`TransientError` so the scheduler's retry sees it."""

    def __init__(self, point: str, index: int):
        super().__init__(f"injected fault at {point} (call #{index})")
        self.point = point
        self.index = index


@dataclasses.dataclass
class FaultRule:
    """When one injection point fires. Exactly one trigger shape applies:
    ``at`` (explicit 1-based call indices) > ``every`` (every n-th call) >
    ``p`` (seeded Bernoulli per call; the default, p=1.0). ``times`` caps
    total fires; ``delay_ms`` turns the fault into a stall (sleep) instead
    of an exception — the slow-drafter / slow-sink shape."""

    point: str
    p: float = 1.0
    seed: int = 0
    at: frozenset[int] = frozenset()
    every: int = 0
    times: int = 0
    delay_ms: float = 0.0

    def should_fire(self, index: int, fired_so_far: int) -> bool:
        if self.times and fired_so_far >= self.times:
            return False
        if self.at:
            return index in self.at
        if self.every:
            return index % self.every == 0
        if self.p >= 1.0:
            return True
        # str-seeded Random is sha512-based — deterministic across runs and
        # platforms (unlike hash()-seeded tuples under PYTHONHASHSEED).
        return random.Random(
            f"{self.seed}|{self.point}|{index}"
        ).random() < self.p


class FaultPlane:
    """A set of :class:`FaultRule` plus per-point call counters and a fired
    log (the test introspection surface: ``plane.episodes`` counts injected
    faults, ``plane.fired_log`` says exactly which call of which point).

    Thread-safe: fault points are consulted from the scheduler thread, the
    prefetch worker, checkpoint writers and concurrent event-log emitters.
    """

    def __init__(self, rules: Iterator[FaultRule] | list[FaultRule] = ()):
        self._rules: dict[str, FaultRule] = {}
        for rule in rules:
            if rule.point not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {rule.point!r}; valid points: "
                    f"{', '.join(sorted(FAULT_POINTS))}"
                )
            if rule.point in self._rules:
                # Same hard-fail policy as unknown points: silently keeping
                # only the last clause would run half the intended drill.
                raise ValueError(
                    f"fault point {rule.point!r} appears twice in the spec"
                )
            self._rules[rule.point] = rule
        self._lock = threading.Lock()
        self.calls: dict[str, int] = {}
        self.fired: dict[str, int] = {}
        self.fired_log: list[tuple[str, int]] = []

    # ---- spec grammar ------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlane":
        """``--fault_spec`` grammar (docs/ROBUSTNESS.md):

            spec   := clause (';' clause)*
            clause := point ':' param (',' param)*   |   point
            param  := 'p=' float | 'seed=' int | 'at=' int('+' int)*
                    | 'every=' int | 'times=' int | 'ms=' float

        Example: ``prefill.error by probability, a dead sink at call 5,
        a 40ms-slow drafter every 3rd propose``::

            serve.prefill:p=0.25,seed=7;obs.emit:at=5;draft.slow:every=3,ms=40
        """
        rules = []
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            point, _, params = clause.partition(":")
            kw: dict = {"point": point.strip()}
            for param in params.split(",") if params else []:
                key, sep, value = param.partition("=")
                key, value = key.strip(), value.strip()
                if not sep:
                    raise ValueError(
                        f"fault_spec param {param!r} is not key=value"
                    )
                if key == "p":
                    kw["p"] = float(value)
                elif key == "seed":
                    kw["seed"] = int(value)
                elif key == "at":
                    kw["at"] = frozenset(int(v) for v in value.split("+"))
                elif key == "every":
                    kw["every"] = int(value)
                elif key == "times":
                    kw["times"] = int(value)
                elif key == "ms":
                    kw["delay_ms"] = float(value)
                else:
                    raise ValueError(
                        f"unknown fault_spec key {key!r} (valid: p, seed, "
                        "at, every, times, ms)"
                    )
            rules.append(FaultRule(**kw))
        return cls(rules)

    # ---- firing ------------------------------------------------------------

    @property
    def episodes(self) -> int:
        with self._lock:
            return len(self.fired_log)

    def fire(self, point: str) -> FaultRule | None:
        """Count one call at ``point``; return its rule iff it fires."""
        with self._lock:
            rule = self._rules.get(point)
            n = self.calls.get(point, 0) + 1
            self.calls[point] = n
            if rule is None or not rule.should_fire(n, self.fired.get(point, 0)):
                return None
            self.fired[point] = self.fired.get(point, 0) + 1
            self.fired_log.append((point, n))
            return rule

    def hook(self, point: str) -> None:
        """The callable :func:`install` plants into leaf modules'
        ``fault_hook`` slots: raise (or stall) iff ``point`` fires."""
        rule = self.fire(point)
        if rule is None:
            return
        if rule.delay_ms:
            time.sleep(rule.delay_ms / 1e3)
            return
        raise InjectedFault(point, self.calls[point])


# --------------------------------------------------------------------------
# global installation (the --fault_spec / test surface)

_PLANE: FaultPlane | None = None


def installed() -> FaultPlane | None:
    return _PLANE


def install(plane: FaultPlane | None) -> None:
    """Make ``plane`` the process-wide fault plane (None = disarm). Leaf
    modules that cannot import this one (obs stays jax-free and
    serve-free; checkpoint/pipeline must not drag the serve stack into
    every train import) expose a ``fault_hook`` module attribute instead —
    installation fills those slots, uninstallation clears them. Install
    BEFORE serving/training threads start (the CLIs install at startup;
    tests use the :func:`active` context manager)."""
    global _PLANE
    _PLANE = plane
    hook = None if plane is None else plane.hook
    from transformer_tpu.data import pipeline
    from transformer_tpu.obs import events
    from transformer_tpu.train import checkpoint

    events.fault_hook = hook
    checkpoint.fault_hook = hook
    pipeline.fault_hook = hook


@contextlib.contextmanager
def active(plane: FaultPlane):
    """Scoped installation — the chaos-test idiom::

        with resilience.active(FaultPlane.parse("serve.prefill:p=0.3")):
            scheduler.run(reqs)
    """
    install(plane)
    try:
        yield plane
    finally:
        install(None)


def maybe_fail(point: str) -> None:
    """The serve-side injection site: no-op without a plane (one global
    load + ``is None`` — the zero-overhead-when-disabled contract), else
    raise/stall per the point's rule. Host-side only, never traced."""
    plane = _PLANE
    if plane is None:
        return
    plane.hook(point)


def fired(point: str) -> bool:
    """Non-raising consultation for data-corruption-shaped points (the
    site mutates its own state when True — e.g. ``prefix.corrupt`` flips a
    stored block byte so the checksum path proves detection end-to-end)."""
    plane = _PLANE
    if plane is None:
        return False
    return plane.fire(point) is not None


# --------------------------------------------------------------------------
# structured error taxonomy (the continuous scheduler's answer contract)

#: code -> meaning; docs/ROBUSTNESS.md carries the full table. Every error
#: the continuous scheduler answers carries one of these under ``"code"``
#: (the grouped path keeps its historical string-only shape).
ERROR_CODES = {
    "validation": "the request itself is unservable (bad field, over-length)",
    "routing": "request kind does not match what this export serves",
    "deadline": "the request's deadline_ms elapsed before completion",
    "cancelled": "the client (or operator) cancelled the request",
    "backpressure": "the admission queue is full (max_backlog)",
    "transient": "a transient fault persisted through the bounded retries",
    "resource": "a device resource budget (paged KV pool) was exhausted "
                "mid-flight; the partial continuation rides along",
    "upgrade": "a live-weights rollout command was refused (torn/mismatched "
               "checkpoint, no coordinator, or a rollout already in flight) "
               "— serving is untouched",
    "internal": "an unexpected failure; the request was isolated",
}


def classify_error(exc: BaseException) -> str:
    """Exception -> taxonomy code for admission-time failures."""
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return "validation"
    return "internal"


def error_answer(code: str, message: str, **extra) -> dict:
    assert code in ERROR_CODES, code
    return {"error": message, "code": code, **extra}


def backoff_ms(base_ms: float, attempt: int, order: int) -> float:
    """Jittered exponential backoff for admission retries: deterministic
    per (order, attempt) — chaos runs replay bit-identically — but spread
    over [0.5, 1.5)x so a herd of same-tick failures does not retry in
    lockstep."""
    jitter = 0.5 + random.Random(f"backoff|{order}|{attempt}").random()
    return base_ms * (2 ** attempt) * jitter
