"""Replica worker: one model copy + one ``ContinuousScheduler`` behind a pipe.

The unit the router (``serve/router.py``) multiplies. Launched as

    python -m transformer_tpu.serve.replica --export_path=model \\
        --tgt_vocab_file=vocab.subwords [scheduler flags...]

it loads its own model copy (or builds a deterministic test model from a
``--model_spec`` JSON — the CI/bench bootstrap), wraps the EXISTING
continuous-batching scheduler around it, and speaks a line-oriented JSON
protocol on stdin/stdout:

router -> replica:
    {"type": "req",      "rid": N, "req": {...}}          serve a request
    {"type": "req",      "rid": N, "req": {...},
     "blocks": ..., "tokens": T}                          ...after a prefill
                                                          handoff (inject T
                                                          prompt tokens' KV
                                                          into the local
                                                          PrefixCache first)
    {"type": "prefill",  "rid": N, "req": {...}}          disaggregation
                                                          stage 1: ingest the
                                                          prompt, export its
                                                          KV blocks, answer
                                                          "prefilled"
    {"type": "export_state", "limit": K}                  supervisor warm-up:
                                                          export the K hottest
                                                          PrefixCache prefixes
    {"type": "inject_state", "entries": [...]}            ...inject them into
                                                          a fresh replica
    {"type": "upgrade", "ckpt": D, "version": V}          live-weights swap:
                                                          verify the manifest
                                                          + structure, stage
                                                          into the two-version
                                                          param slot (the flip
                                                          lands at a drained
                                                          step boundary)
    {"type": "rollback"}                                  re-stage the resident
                                                          previous weights
    {"type": "dump"}                                      flight-recorder dump:
                                                          persist the ring and
                                                          reply "flight"
    {"type": "shutdown"}                                  drain + exit

replica -> router:
    {"type": "ready", "replica": name, "slots": N
     [, "control_port": P]}                               P with --ha only
    {"type": "hb", "backlog": B, "free": F, "active": A}  heartbeat (the
                                                          least-loaded gauges)
    {"type": "answer", "rid": N, "resp": {...}
     [, "slo": {...}]}                                    one per request;
                                                          "slo" is the span
                                                          side channel (ttft
                                                          etc., stripped
                                                          before the client)
    {"type": "prefilled", "rid": N, "tokens": T, "blocks": ...}
    {"type": "prefix_state", "entries": [...]}            export_state reply
    {"type": "state_injected", "tokens": T}               inject_state reply
    {"type": "upgrade_staged", "ok": B, "version": V
     [, "error": E]}                                      upgrade/rollback
                                                          verdict (ok=false =
                                                          refused, old weights
                                                          untouched)
    {"type": "upgraded", "ok": B, "version": V}           the step-boundary
                                                          flip landed (or its
                                                          ckpt.swap abort)
    {"type": "flight", "record": {...}|null}              dump reply: the
                                                          flight-recorder ring
                                                          (obs/flight.py)
    {"type": "stats", "stats": {...}
     [, "perf": {...}]}                                   final, at shutdown;
                                                          "perf" = per-program
                                                          measured rows when
                                                          the profiler is armed

**Router HA** (``--ha``): the worker additionally listens on a localhost
TCP control socket (ephemeral port, announced in ``ready``). A warm-standby
router (``serve/standby.py``) that declares the primary dead connects and
sends a takeover handshake::

    {"type": "takeover", "epoch": E, "inflight": [rid, ...]}

An epoch HIGHER than the channel currently holding authority (stdin starts
at epoch 1) wins: the reply reports, for every rid the standby believes
in-flight here, whether it is ``done`` (with the original answer message
replayed from a bounded recent-answer cache — an answer lost in the dead
primary's pipe is re-delivered, and the standby's order-keyed funnel keeps
at-most-once), still ``inflight`` (it will answer on the NEW channel), or
``unknown`` (the standby re-dispatches it)::

    {"type": "adopted", "replica": name, "epoch": E,
     "statuses": {rid: "done"|"inflight"|"unknown"},
     "messages": {rid: <original answer/prefilled message>}}

and every subsequent worker message flows to the adopting channel. A
takeover with a stale epoch answers ``{"type": "rejected", "epoch": cur}``
and changes nothing — the split-brain guard: after an adoption, requests
still arriving from the OLD channel (a falsely-declared-dead primary) are
dropped and counted, never served twice. In HA mode stdin EOF does NOT
drain the worker (the primary dying must not kill the fleet); shutdown
comes from the authoritative channel (or the supervising process group).

``rid`` is the ROUTER's order for the request — the replica never invents
identity, so the router's order-keyed answer funnel stays authoritative.
Every forwarded request carries the router-minted ``traceparent``; with
``--metrics_jsonl`` + ``--trace`` this replica's spans parent under the
router's ``route.request`` span and ``obs summarize/trace/slo --merge``
re-joins the fleet trace (docs/OBSERVABILITY.md).

**KV handoff format** (disaggregation): the prompt's KV crosses the
process boundary as the prefix cache's OWN host-side token-aligned blocks
(``serve/prefix_cache.py``) — per layer, per ``block_tokens`` positions,
in the cache's storage layout — serialized as base64 ``tobytes`` with
dtype/shape. :func:`export_blocks` reads them out of this replica's
``PrefixCache`` after a ``max_new=0`` admission fed them; the decode side
:func:`inject_blocks` inserts them into ITS cache so admission restores
them with zero model forwards (the prefix-cache byte-parity contract makes
the handoff answer-invariant).

Sharding: on a multi-device host, ``parallel/mesh.py`` machinery shards
each replica's params exactly as ``cli/serve.py`` would — this worker
adds process isolation on top, not a new parallelism scheme. CI runs it
on plain CPU processes.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import queue
import socket
import sys
import threading
import time
from collections import deque


class _Channel:
    """One duplex control link: stdin/stdout, or an accepted takeover
    socket. The MAIN loop is the only writer (lines never tear); reader
    threads only parse the inbound side into the main queue. ``epoch`` is
    the authority the channel last proved (stdin starts at 1; takeover
    sockets earn theirs through the handshake); a write failure marks the
    channel broken — answers are NOT lost with it, the bounded recent-
    answer cache re-delivers them to whoever adopts next."""

    def __init__(self, write_file, name: str, epoch: int = 0):
        self._write = write_file
        self.name = name
        self.epoch = epoch
        self.broken = False

    def send(self, msg: dict) -> bool:
        if self.broken or self._write is None:
            return False
        try:
            self._write.write(json.dumps(msg) + "\n")
            self._write.flush()
            return True
        except (OSError, ValueError):
            self.broken = True
            return False


# --------------------------------------------------------------------------
# deterministic test-model bootstrap (CI, benches)


def build_model_from_spec(spec: dict):
    """(params, cfg, tok) from a model-spec dict — the deterministic
    bootstrap the router tests and benches use: every process (replicas
    AND the in-process single-scheduler reference) that builds the same
    spec gets bit-identical params and vocab, so byte-parity assertions
    are meaningful across process boundaries.

    Spec shape::

        {"config": {...ModelConfig overrides (vocab sizes filled from the
                    corpus tokenizer)...},
         "seed": 0,
         "corpus": ["line", ...],
         "target_vocab_size": 300}
    """
    import jax

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models import transformer_init

    tok = SubwordTokenizer.build_from_corpus(
        list(spec["corpus"]),
        target_vocab_size=int(spec.get("target_vocab_size", 300)),
    )
    cfg = ModelConfig(
        **{
            **dict(spec.get("config", {})),
            "input_vocab_size": tok.model_vocab_size,
            "target_vocab_size": tok.model_vocab_size,
        }
    )
    params = transformer_init(jax.random.PRNGKey(int(spec.get("seed", 0))), cfg)
    return params, cfg, tok


# --------------------------------------------------------------------------
# KV-block handoff (disaggregated prefill/decode)


def _encode_array(a) -> dict:
    import numpy as np

    a = np.ascontiguousarray(a)
    return {
        "dtype": a.dtype.str,
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(d: dict):
    import numpy as np

    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"])


def export_blocks(cache, ids: "list[int]") -> "tuple[int, list]":
    """Read the longest block-aligned prefix of ``ids`` out of ``cache``
    (a ``PrefixCache`` a ``max_new=0`` admission just fed) as the wire
    payload: ``payload[j]`` is block j — per-layer dicts of serialized
    arrays in the cache's own storage layout. Returns ``(tokens,
    payload)``; (0, []) when nothing aligned is stored (budget pressure) —
    the decode side then simply full-prefills."""
    B = cache.block_tokens
    aligned = (len(ids) // B) * B
    if not aligned:
        return 0, []
    hit = cache.match(ids[:aligned])
    try:
        payload = []
        for node in hit._nodes:
            try:
                # host_blocks_for serves both tiers: stored host blocks
                # directly, device-resident blocks via ONE ephemeral pool
                # read (paged serving) — the wire format is identical.
                blocks = cache.host_blocks_for(node)
            except Exception:  # noqa: BLE001  # tpa: disable=TPA006 — wire export is best-effort: an unreadable block truncates the payload to the readable prefix (the decode side full-prefills the rest), it must never kill the handoff
                break
            payload.append(
                [
                    {key: _encode_array(layer[key]) for key in sorted(layer)}
                    for layer in blocks
                ]
            )
        return len(payload) * B, payload
    finally:
        hit.release()


def inject_blocks(cache, ids: "list[int]", tokens: int, payload: list) -> int:
    """Insert a handoff payload into the local ``PrefixCache`` so the next
    admission of ``ids`` restores it without a model forward. Returns the
    tokens actually inserted (the cache's budget may admit fewer)."""
    B = cache.block_tokens
    tokens = min(int(tokens), (len(ids) // B) * B, len(payload) * B)
    if tokens <= 0:
        return 0
    blocks = [
        [
            {key: _decode_array(d) for key, d in layer.items()}
            for layer in blk
        ]
        for blk in payload
    ]
    cache.insert(ids[:tokens], tokens, lambda start: blocks[start // B])
    return tokens


# --------------------------------------------------------------------------
# the worker


def _parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="router replica worker")
    p.add_argument("--replica_name", default="replica0")
    p.add_argument("--role", choices=("both", "prefill", "decode"),
                   default="both")
    p.add_argument("--export_path", default="")
    p.add_argument("--tgt_vocab_file", default="")
    p.add_argument("--model_spec", default="",
                   help="JSON file with a deterministic test-model spec "
                        "(build_model_from_spec) — CI/bench bootstrap")
    p.add_argument("--kv_cache_int8", action="store_true")
    p.add_argument("--serve_slots", type=int, default=4)
    p.add_argument("--serve_max_total", type=int, default=0)
    p.add_argument("--prefill_chunk", type=int, default=0)
    p.add_argument("--max_len", type=int, default=64,
                   help="default max_new per request")
    p.add_argument("--speculate_k", type=int, default=0)
    p.add_argument("--prefix_cache_mb", type=int, default=0)
    p.add_argument("--prefix_block", type=int, default=16)
    p.add_argument("--kv_layout", choices=("dense", "paged"), default="dense",
                   help="per-slot KV storage: dense max_total buffers, or "
                        "the paged block pool (docs/SERVING.md)")
    p.add_argument("--kv_pool_blocks", type=int, default=0,
                   help="paged pool size in blocks (0 = full provisioning)")
    p.add_argument("--mesh", default="",
                   help="serving mesh size ('N' or 'data=N'): the replica "
                        "becomes ONE pjit program over N devices — params "
                        "replicated by the partition rules, KV pool sharded "
                        "on its storage axis (docs/SERVING.md 'Sharded "
                        "replicas'). On CPU the worker grows its own "
                        "virtual platform before jax initializes. '' = "
                        "single-device (historical)")
    p.add_argument("--max_backlog", type=int, default=0)
    p.add_argument("--heartbeat_ms", type=float, default=200.0)
    p.add_argument("--metrics_jsonl", default="")
    p.add_argument("--trace", action="store_true")
    p.add_argument("--fault_spec", default="")
    p.add_argument("--ha", action="store_true",
                   help="router HA: listen on a localhost control socket "
                        "for a warm standby's takeover handshake, and "
                        "survive stdin EOF (the primary dying must not "
                        "kill the fleet)")
    p.add_argument("--init_ckpt", default="",
                   help="bootstrap the serving weights from this "
                        "manifest-verified checkpoint instead of the "
                        "export/spec weights — the supervisor passes the "
                        "fleet's TARGET version here so a respawn never "
                        "resurrects stale weights (serve/upgrade.py)")
    p.add_argument("--weight_version", default="",
                   help="expected weight_version digest for --init_ckpt "
                        "(mismatch refuses the bootstrap loudly); also "
                        "tags an un-upgraded replica's answers")
    return p.parse_args(argv)


def stdin_reader(q: "queue.Queue") -> None:
    """Feed stdin lines into ``q``, then a ``None`` EOF sentinel — the one
    line-intake reader shared by this worker, ``cli/serve.py``, and
    ``cli/router.py`` (all three speak the same line protocol, so EOF and
    encoding behavior must never diverge between them)."""
    for line in sys.stdin:
        q.put(line)
    q.put(None)


def _control_server(listener: socket.socket, q: "queue.Queue") -> None:
    """Accept takeover connections; per connection, one reader thread
    feeds parsed ``(channel, line)`` pairs into the main queue — exactly
    the stdin_reader contract, so the main loop stays the only owner of
    every piece of serving state (the TPA101 surface between the control
    threads and the loop is the synchronized queue alone)."""
    while True:
        try:
            conn, _ = listener.accept()
        except OSError:
            return  # listener closed at shutdown
        chan = _Channel(
            conn.makefile("w", encoding="utf-8", buffering=1),
            name="takeover",
        )
        rf = conn.makefile("r", encoding="utf-8")

        def reader(chan=chan, rf=rf):
            try:
                for line in rf:
                    q.put((chan, line))
            except (OSError, ValueError):
                pass
            chan.broken = True

        threading.Thread(
            target=reader, daemon=True, name="replica-control-read"
        ).start()


def main(argv=None) -> None:
    args = _parse_args(argv)
    # --mesh bootstrap must precede the FIRST jax-importing line: on the
    # CPU platform the worker grows its own virtual device count (the
    # conftest/analysis trick), which only takes effect before jax
    # initializes. The flag only affects CPU hosts — on TPU it is inert —
    # and an operator-provided device-count flag always wins.
    from transformer_tpu.serve.sharded import (
        normalize_mesh_spec,
        parse_mesh_spec,
    )

    mesh_n = parse_mesh_spec(args.mesh)
    mesh_shape = normalize_mesh_spec(args.mesh)
    if mesh_n is not None and mesh_n > 1:
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = (
                xla_flags
                + f" --xla_force_host_platform_device_count={mesh_n}"
            ).strip()
    if args.fault_spec:
        from transformer_tpu.serve import resilience

        resilience.install(resilience.FaultPlane.parse(args.fault_spec))

    telemetry = None
    if args.metrics_jsonl:
        from transformer_tpu.obs import EventLog, Telemetry
        from transformer_tpu.obs.flight import flight_path_for

        telemetry = Telemetry(
            events=EventLog(args.metrics_jsonl), trace=args.trace
        )
        telemetry.arm_profiler()
        # Tight autodump: the on-disk flight record is all a SIGKILL
        # leaves behind, and the Supervisor's postmortem capture reads it
        # — half a second bounds how much of the victim's last telemetry
        # the fleet can lose (docs/OBSERVABILITY.md).
        flight = telemetry.arm_flight(
            flight_path_for(args.metrics_jsonl), autodump_s=0.5
        )
        flight.install_signal_handlers()

    if args.model_spec:
        with open(args.model_spec) as f:
            spec = json.load(f)
        params, cfg, tok = build_model_from_spec(spec)
    else:
        from transformer_tpu.cli.translate import load_export
        from transformer_tpu.data.tokenizer import SubwordTokenizer

        params, cfg = load_export(
            args.export_path, kv_cache_int8=args.kv_cache_int8
        )
        tok = SubwordTokenizer.load(args.tgt_vocab_file)

    weight_version = args.weight_version or None
    if args.init_ckpt:
        # Verified-integrity bootstrap at the fleet's target version: the
        # checkpoint's manifest is byte-verified and its arrays matched
        # against the spec-built tree (shape/dtype twins) BEFORE the swap
        # — a bad artifact kills the bootstrap loudly so the supervisor's
        # crash-loop budget (not a silently wrong fleet) absorbs it.
        from transformer_tpu.serve.upgrade import load_checkpoint_params

        params, loaded_version = load_checkpoint_params(
            args.init_ckpt, params
        )
        if args.weight_version and args.weight_version != loaded_version:
            print(
                f"replica: --init_ckpt {args.init_ckpt} verifies to "
                f"{loaded_version} but --weight_version expected "
                f"{args.weight_version}; refusing to serve the wrong "
                "weights", file=sys.stderr,
            )
            raise SystemExit(2)
        weight_version = loaded_version

    from transformer_tpu.serve import ContinuousScheduler, PrefixCache

    prefix_cache = None
    disaggregated = args.role in ("prefill", "decode")
    if args.prefix_cache_mb > 0 or disaggregated:
        # Disaggregation rides the prefix-cache block format on BOTH
        # sides: the prefill worker exports through its cache, the decode
        # worker injects into its own — so both roles get one by default.
        prefix_cache = PrefixCache(
            cfg,
            block_tokens=args.prefix_block,
            budget_mb=max(1, args.prefix_cache_mb or 64),
        )
    # Span side channel: the scheduler hands every answer-boundary span
    # dict to this tap (host-side, jaxpr-inert); flush_answers ships the
    # latency/prefix numbers next to the answer so the ROUTER's SLO engine
    # (the autoscaling signal) sees real per-request ttft without each
    # replica needing its own telemetry sink.
    spans_by_order: "dict[int, dict]" = {}
    sched = ContinuousScheduler(
        params, cfg, tok,
        num_slots=args.serve_slots,
        max_total=args.serve_max_total or None,
        prefill_chunk=args.prefill_chunk,
        default_max_new=args.max_len,
        telemetry=telemetry,
        speculate_k=args.speculate_k,
        prefix_cache=prefix_cache,
        max_backlog=args.max_backlog,
        kv_layout=args.kv_layout,
        kv_block=args.prefix_block,
        kv_pool_blocks=args.kv_pool_blocks,
        mesh=mesh_n,
        weight_version=weight_version,
        span_tap=lambda span: spans_by_order.__setitem__(
            span.get("order"), span
        ),
    )

    q: queue.Queue = queue.Queue()
    threading.Thread(target=stdin_reader, args=(q,), daemon=True).start()
    stdin_chan = _Channel(sys.stdout, "stdin", epoch=1)
    epoch = 1
    out = stdin_chan  # the authoritative outbound channel
    control_port = None
    if args.ha:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(4)
        control_port = listener.getsockname()[1]
        threading.Thread(
            target=_control_server, args=(listener, q), daemon=True,
            name="replica-control-accept",
        ).start()
    ready = {
        "type": "ready", "replica": args.replica_name,
        "slots": args.serve_slots, "role": args.role,
    }
    if control_port is not None:
        ready["control_port"] = control_port
    if weight_version is not None:
        ready["weight_version"] = weight_version
    if mesh_shape is not None:
        # Canonical mesh shape ('data=N'): the supervisor compares this
        # against its expected_mesh and refuses a wrong-shape respawn
        # BEFORE the replica takes traffic.
        ready["mesh"] = mesh_shape
    out.send(ready)

    hb_s = max(args.heartbeat_ms, 1.0) / 1e3
    last_hb = 0.0
    # rid bookkeeping: the scheduler answers in arrival order and this
    # loop is the only submitter, so a FIFO of (rid, scheduler order)
    # (parallel to the submission sequence) maps drained responses back to
    # router orders and their tapped spans.
    rid_fifo: "list[tuple[int, int]]" = []
    prefill_rids: "set[int]" = set()
    prompt_ids: "dict[int, list[int]]" = {}
    # Bounded re-delivery cache: the full outbound message per answered
    # rid, replayed through the takeover handshake when an answer died in
    # the old primary's pipe (the adopting funnel dedupes, so replaying is
    # always safe).
    recent_answers: "dict[int, dict]" = {}
    answer_fifo: deque = deque()
    # At most one in-flight checkpoint verification (upgrade_staged is
    # answered by the main loop once the loader thread finishes — the
    # handoff is the is-alive check, so the loop never blocks on I/O).
    upgrade_load: "list[tuple[threading.Thread, dict]]" = []
    stats_extra = {"stale_dropped": 0, "takeovers": 0, "rejected_takeovers": 0}

    def _reap_upgrade_load() -> None:
        if not upgrade_load or upgrade_load[0][0].is_alive():
            return
        _, holder = upgrade_load.pop(0)
        if holder["error"] is not None:
            out.send({
                "type": "upgrade_staged", "ok": False,
                "version": holder["version"], "error": holder["error"],
            })
            return
        new_params, digest = holder["result"]
        try:
            sched.stage_params(new_params, digest)
        except ValueError as e:
            out.send({
                "type": "upgrade_staged", "ok": False, "version": digest,
                "error": f"{type(e).__name__}: {e}",
            })
            return
        out.send({"type": "upgrade_staged", "ok": True, "version": digest})

    def _remember(rid, msg) -> None:
        recent_answers[rid] = msg
        answer_fifo.append(rid)
        while len(answer_fifo) > 512:
            recent_answers.pop(answer_fifo.popleft(), None)

    def handle_takeover(chan: _Channel, msg: dict) -> None:
        nonlocal epoch, out
        e = int(msg.get("epoch", 0))
        if e <= epoch:
            # Split-brain guard: a stale or duplicate adopter changes
            # nothing — the current authority keeps the worker.
            stats_extra["rejected_takeovers"] += 1
            chan.send({
                "type": "rejected", "replica": args.replica_name,
                "epoch": epoch,
            })
            return
        statuses: dict = {}
        messages: dict = {}
        inflight_here = {rid for rid, _ in rid_fifo}
        for rid in msg.get("inflight", []):
            if rid in recent_answers:
                statuses[str(rid)] = "done"
                messages[str(rid)] = recent_answers[rid]
            elif rid in inflight_here:
                statuses[str(rid)] = "inflight"
            else:
                statuses[str(rid)] = "unknown"
        epoch = e
        chan.epoch = e
        out = chan
        stats_extra["takeovers"] += 1
        out.send({
            "type": "adopted", "replica": args.replica_name, "epoch": e,
            "role": args.role, "slots": args.serve_slots,
            "statuses": statuses, "messages": messages,
            "backlog": sched.backlog, "active": sched.active_count,
        })

    def ingest(chan: _Channel, msg: dict) -> bool:
        """Handle one control message; returns False on shutdown."""
        kind = msg.get("type")
        if kind == "takeover":
            handle_takeover(chan, msg)
            return True
        if chan.epoch < epoch:
            # A channel that lost authority (the falsely-declared-dead
            # primary of a completed takeover): its requests must not be
            # served TWICE — drop and count.
            stats_extra["stale_dropped"] += 1
            return True
        if kind == "shutdown":
            sched.shutdown()
            return False
        if kind == "dump":
            # Explicit flight-recorder dump: persist the ring AND ship the
            # record back over the wire — the Supervisor prefers the wire
            # copy (fresher than the last autodump) when both exist.
            record = None
            if telemetry is not None and telemetry.flight is not None:
                record = telemetry.flight.dump("request")
            out.send({"type": "flight", "record": record})
            return True
        if kind == "export_state":
            entries = []
            if prefix_cache is not None:
                for ids in prefix_cache.hot_prefixes(
                    int(msg.get("limit", 8))
                ):
                    try:
                        tokens, payload = export_blocks(
                            prefix_cache, list(ids)
                        )
                    except Exception:  # tpa: disable=TPA006 — warm-up export is best-effort: a corrupt/evicted prefix is skipped, the newcomer just starts colder
                        continue
                    if tokens:
                        entries.append({
                            "ids": list(ids), "tokens": tokens,
                            "blocks": payload,
                        })
            out.send({"type": "prefix_state", "entries": entries})
            return True
        if kind == "upgrade":
            # Stage a verified weight swap (serve/upgrade.py): byte-verify
            # the checkpoint's manifest, match it against the RUNNING
            # params (structure/shape/dtype), confirm the coordinator's
            # expected digest, then hand it to the scheduler's two-version
            # slot. Verification (full npz read + per-array crc32) runs on
            # a WORKER THREAD — a multi-GB checkpoint must not starve this
            # loop's heartbeats, or the router's liveness sweep would fail
            # the quiesced replica over mid-swap. The main loop collects
            # the result (_reap_upgrade_load) and stages it; the actual
            # flip happens at a drained step boundary — the "upgraded"
            # message reports it. ANY failure answers a structured refusal
            # with the old weights untouched.
            version = msg.get("version")
            if upgrade_load:
                out.send({
                    "type": "upgrade_staged", "ok": False,
                    "version": version,
                    "error": "an upgrade is already being verified",
                })
                return True
            holder = {
                "version": version, "result": None, "error": None,
            }

            def _load(ckpt=str(msg.get("ckpt", "")), holder=holder):
                try:
                    from transformer_tpu.serve.upgrade import (
                        UpgradeError,
                        load_checkpoint_params,
                    )

                    new_params, digest = load_checkpoint_params(
                        ckpt, sched.params
                    )
                    expected = holder["version"]
                    if expected and digest != expected:
                        raise UpgradeError(
                            f"checkpoint verifies to {digest} but the "
                            f"rollout targets {expected} — wrong artifact"
                        )
                    holder["result"] = (new_params, digest)
                except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006 — rejection IS the contract: a torn/mismatched checkpoint must become one structured refusal with serving untouched, never a dead worker
                    holder["error"] = f"{type(e).__name__}: {e}"

            t = threading.Thread(
                target=_load, daemon=True, name="replica-upgrade-load"
            )
            t.start()
            upgrade_load.append((t, holder))
            return True
        if kind == "rollback":
            # Re-stage the resident previous weights (the second buffer a
            # completed swap left behind) — the canary-rollback path.
            try:
                version = sched.stage_rollback()
            except ValueError as e:
                out.send({
                    "type": "upgraded", "ok": False, "version": None,
                    "error": f"{type(e).__name__}: {e}",
                })
                return True
            out.send({
                "type": "upgrade_staged", "ok": True, "version": version,
                "rollback": True,
            })
            return True
        if kind == "inject_state":
            total = 0
            for e in msg.get("entries", []):
                try:
                    total += inject_blocks(
                        prefix_cache, list(e["ids"]), e.get("tokens", 0),
                        e.get("blocks", []),
                    ) if prefix_cache is not None else 0
                except Exception:  # tpa: disable=TPA006 — a corrupt warm-up payload degrades to a cold cache, never a dead worker
                    pass
            out.send({"type": "state_injected", "tokens": total})
            return True
        if kind not in ("req", "prefill"):
            return True
        rid = msg.get("rid")
        req = msg.get("req")
        if not isinstance(req, dict):
            req = {"prompt": ""}
        if kind == "prefill":
            # Disaggregation stage 1: ingest the prompt only (max_new=0
            # feeds the prefix cache at retirement), then export its KV.
            req = {**req, "max_new": 0, "cache_prefix": True}
            prefill_rids.add(rid)
        if prefix_cache is not None:
            try:
                ids = [tok.bos_id, *tok.encode(str(req.get("prompt", "")))]
            except Exception:  # tpa: disable=TPA006 — the scheduler's admission answers the validation error; the handoff bookkeeping just skips it
                ids = []
            prompt_ids[rid] = ids
            if kind == "req" and msg.get("blocks") and ids:
                try:
                    inject_blocks(
                        prefix_cache, ids, msg.get("tokens", 0),
                        msg["blocks"],
                    )
                except Exception:  # tpa: disable=TPA006 — a corrupt handoff payload degrades to full prefill (the cache just misses); it must never kill the worker
                    pass
        order = sched.submit(req)
        rid_fifo.append((rid, order))
        return True

    def flush_answers() -> None:
        for resp in sched.drain_ready():
            rid, order = rid_fifo.pop(0)
            span = spans_by_order.pop(order, None)
            if rid in prefill_rids:
                prefill_rids.discard(rid)
                tokens, payload = 0, []
                ids = prompt_ids.pop(rid, [])
                if "error" not in resp and prefix_cache is not None and ids:
                    try:
                        tokens, payload = export_blocks(prefix_cache, ids)
                    except Exception:  # tpa: disable=TPA006 — export is best-effort: a failed handoff falls back to full prefill on the decode side
                        tokens, payload = 0, []
                msg = {
                    "type": "prefilled", "rid": rid,
                    "tokens": tokens, "blocks": payload,
                }
            else:
                prompt_ids.pop(rid, None)
                msg = {"type": "answer", "rid": rid, "resp": resp}
                if span is not None:
                    # The side channel the router's SLO engine feeds on —
                    # never merged into resp (client answers stay
                    # byte-identical to a single scheduler's).
                    msg["slo"] = {
                        k: span[k]
                        for k in (
                            "ttft_s", "queue_s", "total_s",
                            "prefix_hit_tokens", "new_tokens",
                        )
                        if k in span
                    }
            _remember(rid, msg)
            out.send(msg)

    alive = True
    while alive or sched.busy:
        # Ingest whatever the router already sent; block only when idle.
        while alive:
            try:
                if sched.busy or sched.has_ready:
                    item = q.get_nowait()
                else:
                    # Idle: block, but wake often enough that heartbeats
                    # keep flowing (the router's liveness gauge).
                    item = q.get(timeout=hb_s)
            except queue.Empty:
                break
            if item is None:
                # stdin EOF: in HA mode the worker outlives its primary —
                # a standby adopts through the control socket; without HA
                # the historical drain-and-exit contract holds.
                if not args.ha:
                    alive = False
                    break
                continue
            if isinstance(item, str):
                chan, line = stdin_chan, item
            else:
                chan, line = item
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if not isinstance(msg, dict):
                continue
            if not ingest(chan, msg):
                alive = False
                break
        _reap_upgrade_load()
        sched.admit()
        sched.step()
        sched.idle_backoff()
        flush_answers()
        for ev in sched.consume_swap_events():
            # The step-boundary flip (or its ckpt.swap-injected abort)
            # just happened: report it so the coordinator re-admits (or
            # aborts the rollout). ``ok``/``version``/``error`` ride
            # through verbatim.
            out.send({"type": "upgraded", **ev})
        now = time.monotonic()
        if now - last_hb >= hb_s:
            last_hb = now
            hb = {
                "type": "hb",
                "backlog": sched.backlog,
                "free": sched.num_slots - sched.active_count,
                "active": sched.active_count,
            }
            if sched.weight_version is not None:
                hb["wv"] = sched.weight_version
            if mesh_shape is not None:
                hb["mesh"] = mesh_shape
            out.send(hb)
    flush_answers()
    final = {"type": "stats", "stats": {**dict(sched.stats), **stats_extra}}
    if telemetry is not None and telemetry.profiler is not None:
        # Measured per-program rows ride the clean-shutdown stats so the
        # router benchmarks read p50s without re-parsing replica JSONLs.
        final["perf"] = telemetry.profiler.summary()
    out.send(final)
    if telemetry is not None:
        telemetry.close()


if __name__ == "__main__":
    main()
