"""Multi-replica serving tier: the front-end router.

Everything serve-side up to PR 9 was one process wrapping one
``ContinuousScheduler``. This module is the scale-out step the ROADMAP
gates on: a front-end :class:`Router` that owns client intake (the same
``submit``/``submit_done``/``drain_ready`` surface the scheduler exposes,
plus the line-oriented loop in ``cli/router.py``) and dispatches to N
replica workers (``serve/replica.py``), each running the existing
scheduler over its own model copy — plain CPU processes in CI, per-replica
sharded processes (``parallel/mesh.py``) on real pods. Mesh-TensorFlow
(PAPERS.md) grounds the sharded-replica story; the one-write-head paper's
cheap-KV argument is why N per-replica slot pools stay affordable.

Dispatch policy — **prefix affinity first, least-loaded fallback**:

- The prompt's leading ``affinity_block``-aligned token blocks are hashed
  (the same block alignment the prefix cache keys on), and the request is
  routed by rendezvous hashing over the healthy replicas — repeated system
  prompts land on the replica whose ``PrefixCache`` is already warm, and a
  replica death only remaps the keys it owned.
- When the affine replica is unhealthy, or its load (router-assigned
  in-flight + heartbeat backlog) exceeds the least-loaded replica's by
  more than ``affinity_slack``, the request falls back to least-loaded.
  Load is fed by replica heartbeats (backlog/free-slot gauges over the
  control channel) topped up with the router's own assignment counts
  between beats.

**Zero-loss failover**: every dispatched-but-unanswered request is tracked
in an order-keyed in-flight table. A replica death (pipe EOF, send
failure, process exit, missed heartbeats — all feeding a per-replica
:class:`~transformer_tpu.serve.resilience.CircuitBreaker`) re-enqueues its
victims at the FRONT of the pending queue in their original order, with
their original trace id and deadline intact; redispatch is bounded
(``max_redispatch``) and exhaustion answers a structured ``transient``
error. A failed-over worker whose PROCESS still runs (a heartbeat-timeout
victim: GC pause, slow step) earns its way back: when a fresh heartbeat
arrives after the death mark and the breaker's cooldown has elapsed, the
half-open probe re-admits the link (``route.revive``) and its first
answered request closes the breaker — exited/SIGKILLed workers stay dead. **At-most-once answers** are enforced by the router's order-keyed
answer funnel: an answer for an order that is already answered (or already
drained) is counted and dropped, so the benign race of a replica answering
just before its death can never double-answer a client.

**Tracing**: every request gets a router-minted trace identity
(:class:`~transformer_tpu.obs.trace.SpanContext`, parented under an
incoming client ``traceparent`` when one is present) and every forwarded
request carries it as the W3C ``traceparent`` header — the replica's
``serve.request`` root parents under the router's ``route.request`` span,
so ``python -m transformer_tpu.obs summarize/trace/slo --merge`` re-joins
one request's spans across the router's and every replica's JSONL log
(docs/OBSERVABILITY.md "Multi-source merge"). ``route.dispatch`` /
``route.failover`` events carry the victim trace ids.

**Disaggregated prefill/decode** (``disaggregate=True``): replicas are
marked prefill-only or decode-only. A request is first sent to a prefill
replica, which ingests the prompt (``max_new=0``) and hands back the
prompt's KV as host-side token-aligned blocks in the prefix-cache block
format (``serve/replica.py`` ``export_blocks``); the router forwards the
request plus blocks to a decode replica, which injects them into its own
``PrefixCache`` so admission restores them without a model forward.
Greedy answers stay byte-identical (the prefix-cache parity contract);
losing either side mid-handoff degrades to a full prefill on a decode
replica, never to a lost request.

Threading contract (linted by TPA101–105 and explored by
``analysis/schedules.py router_dispatch_tables``): client threads call
``submit``/``submit_done``/``drain_ready`` under the intake lock
(exactly the scheduler's intake split); per-replica READER threads
only parse pipe lines into the router's inbox ``queue.Queue`` and touch
no other router state; all dispatch/answer/liveness tables are owned by
the single router thread driving :meth:`pump`. Nothing in this module
touches jax — the router process stays model-free (the tokenizer is the
only vocabulary it needs, for affinity hashing): no weights loaded, no
programs compiled, so it restarts cheaply and survives replica OOMs
untouched.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import queue
import subprocess
import sys
import threading
import time
from collections import deque

from transformer_tpu.obs.trace import SpanContext
from transformer_tpu.serve.resilience import (
    CircuitBreaker,
    InjectedFault,
    error_answer,
    maybe_fail,
)


def affinity_key(ids, block: int) -> "int | None":
    """Hash of the prompt's leading ``block``-aligned token blocks — the
    prefix the replica-side ``PrefixCache`` would match (the prompt minus
    its last token, rounded down to whole blocks, mirroring
    ``PrefixCache.match``'s ``ids[:L-1]`` contract). None when the prompt
    is shorter than one block: there is no shared prefix worth pinning, so
    the request routes least-loaded."""
    if block < 1:
        return None
    aligned = ((len(ids) - 1) // block) * block
    if aligned < block:
        return None
    digest = hashlib.blake2b(
        ("/".join(str(i) for i in ids[:aligned])).encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


def _rendezvous(key: int, name: str) -> int:
    """Highest-random-weight score of (affinity key, replica name): each
    key independently ranks every replica, so removing a dead replica
    remaps ONLY the keys it owned — the warm prefix caches on survivors
    keep their traffic."""
    digest = hashlib.blake2b(
        key.to_bytes(8, "big") + name.encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class _RouterLineError(ValueError):
    """Line-intake routing/parse failure, answered with the bare message
    (byte-identical to ``cli/serve.py``'s grouped-path kind-mismatch
    answers — the router must not change what a bad line reads back)."""


def parse_router_line(line: str) -> dict:
    """One stdin line -> LM request dict for the router (raises
    :class:`_RouterLineError` with the exact message shapes
    ``cli/serve.py`` answers with — the router serves LM exports only, so
    the kind-mismatch wording matches ``_route_lm_request``)."""
    if line.startswith("{"):
        req = json.loads(line)
        if not isinstance(req, dict):
            raise ValueError("request must be a JSON object")
    else:
        req = {"prompt": line}
    if "src" in req:
        raise _RouterLineError("LM export serves 'prompt', not 'src'")
    if "prompt" not in req:
        if "fill" in req:
            raise _RouterLineError("LM export serves 'prompt', not 'fill'")
        raise _RouterLineError(
            "request needs 'src' (seq2seq), 'prompt' (LM) or "
            "'fill' (masked-LM)"
        )
    return req


@dataclasses.dataclass
class _Tracked:
    """One accepted request, from submit to its exactly-once answer."""

    order: int
    req: dict
    ctx: SpanContext            # router-minted trace identity (stable
    #                             across redispatches — the failover
    #                             contract: original order, trace id and
    #                             deadline ride every re-submission)
    t_submit: float
    deadline: float | None      # absolute perf_counter, or None
    affinity: int | None
    attempts: int = 0           # total dispatch count (incl. the disagg
    #                             prefill->decode stage progression)
    redispatches: int = 0       # failover-driven re-dispatches only —
    #                             what max_redispatch bounds and the
    #                             route.dispatch event reports
    refailed: bool = False      # the NEXT dispatch is a failover
    #                             redispatch (set by _fail_replica)
    replica: int | None = None  # current assignment (None = pending)
    t_dispatch: float | None = None   # first dispatch (queue-latency edge)
    stage: str = "decode"       # disaggregation: "prefill" -> "decode"
    blocks: object = None       # prefill handoff payload (opaque to us)
    blocks_tokens: int = 0
    span_root: object = None    # tracing only (None without a tracer)


class ReplicaLink:
    """The router's handle on one replica worker: an outbound ``send``
    plus liveness/load bookkeeping. The subprocess transport is
    :class:`ReplicaProcess`; tests and the deterministic-schedule scenario
    substitute in-process fakes with the same three-method surface
    (``send`` / ``alive`` / ``close``)."""

    def __init__(self, index: int, name: str, role: str = "both"):
        self.index = index
        self.name = name
        self.role = role            # "both" | "prefill" | "decode"
        # Router-thread-owned load/liveness bookkeeping (heartbeat-fed,
        # topped up by the router's own assignment counts between beats).
        self.inflight = 0
        self.hb_backlog = 0
        self.hb_free = 0
        self.hb_active = 0
        self.last_hb: float | None = None
        self.dispatched = 0
        self.answered = 0
        self.dead = False
        self.died_at: float | None = None  # monotonic death mark: only a
        #                                    heartbeat NEWER than this can
        #                                    revive the link
        # Supervision states (serve/supervisor.py): a warming replacement
        # is bootstrapping/cache-warming and takes no traffic yet; a
        # draining victim finishes its in-flight work, then retires for
        # good (retired links are never respawned or revived).
        self.warming = False
        self.draining = False
        self.retired = False
        # Live-weights rollout state (serve/upgrade.py): an `upgrading`
        # link is quiescing/swapping and takes no new dispatches (its
        # in-flight work finishes on its admission-time weights); `wv` is
        # the replica's last-confirmed weight_version tag (ready/hb/
        # upgraded messages), None until the fleet is version-tagged.
        self.upgrading = False
        self.wv: str | None = None
        # Sharded-replica shape (serve/sharded.py): the canonical
        # 'data=N' string the replica's ready/hb messages report, None
        # for a single-device worker. The Supervisor's expected_mesh
        # check reads this at admission — the router itself never sees
        # device topology beyond the string.
        self.mesh: str | None = None
        self.control_port: int | None = None  # --ha takeover socket
        self.final_stats: dict | None = None  # replica's shutdown report
        self.final_perf: dict | None = None   # profiler rows in that report
        # Flight-recorder hooks (obs/flight.py): where this worker's
        # on-disk dumps land (parsed from --metrics_jsonl at spawn), and
        # the last record it shipped over the wire (a `dump` reply) — the
        # Supervisor's postmortem capture reads these.
        self.metrics_jsonl: str | None = None
        self.flight_record: dict | None = None

    # -- transport surface (overridden by real links) -----------------------

    def send(self, msg: dict) -> None:
        raise NotImplementedError

    def alive(self) -> bool:
        """TRANSPORT liveness only (is the worker process still running?);
        the router's failover policy lives in ``dead``, which the revival
        path can clear again — so this must not consult it."""
        return True

    def close(self) -> None:
        pass

    def kill(self) -> None:
        """Force the worker down (supervisor slot reclaim); transports
        without a process are a no-op."""

    def serves(self, stage: str) -> bool:
        return self.role == "both" or self.role == stage


class ReplicaProcess(ReplicaLink):
    """A replica worker as a subprocess speaking JSONL over its pipes.

    The reader thread's ONLY job is parsing stdout lines into the router's
    inbox (and an ``exit`` sentinel on EOF) — every other piece of state
    on this object is owned by the router thread, so the TPA101 shared-
    state surface between the two is exactly the synchronized queue."""

    def __init__(self, index: int, name: str, argv: list[str],
                 role: str = "both"):
        super().__init__(index, name, role=role)
        self._proc = subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, bufsize=1,
        )

    @classmethod
    def spawn(cls, index: int, worker_args: list[str], role: str = "both",
              name: str | None = None) -> "ReplicaProcess":
        """Launch ``python -m transformer_tpu.serve.replica`` with
        ``worker_args`` plus the replica's identity flags."""
        name = name or f"replica{index}"
        argv = [
            sys.executable, "-m", "transformer_tpu.serve.replica",
            "--replica_name", name, "--role", role, *worker_args,
        ]
        link = cls(index, name, argv, role=role)
        # Remember where the worker's flight dumps will land (both
        # `--metrics_jsonl PATH` and `--metrics_jsonl=PATH` spellings):
        # the Supervisor salvages <path>.flight.json after a hard kill.
        for i, arg in enumerate(worker_args):
            if arg == "--metrics_jsonl" and i + 1 < len(worker_args):
                link.metrics_jsonl = worker_args[i + 1] or None
            elif arg.startswith("--metrics_jsonl="):
                link.metrics_jsonl = arg.split("=", 1)[1] or None
        return link

    def start_reader(self, inbox: "queue.Queue") -> None:
        threading.Thread(
            target=self._read_loop, args=(inbox, self._proc.stdout),
            name=f"router-read-{self.name}", daemon=True,
        ).start()

    def _read_loop(self, inbox: "queue.Queue", stdout) -> None:
        for line in stdout:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue  # torn final line of a dying replica
            if isinstance(msg, dict):
                inbox.put((self.index, msg))
        # The pid stamps the sentinel so a supervisor-respawned REPLACEMENT
        # at this index is never failed over by its predecessor's EOF (the
        # old reader thread can outlive the link swap).
        inbox.put((self.index, {"type": "exit", "pid": self._proc.pid}))

    def send(self, msg: dict) -> None:
        stdin = self._proc.stdin
        if stdin is None or self._proc.poll() is not None:
            raise BrokenPipeError(f"replica {self.name} is gone")
        stdin.write(json.dumps(msg) + "\n")
        stdin.flush()

    def alive(self) -> bool:
        return self._proc.poll() is None

    def pid(self) -> int:
        return self._proc.pid

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()

    def close(self, timeout: float = 10.0) -> None:
        try:
            self.send({"type": "shutdown"})
        except (OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()


class Router:
    """Front-end dispatcher over N replica links.

    Client surface (any thread, intake-locked): :meth:`submit` /
    :meth:`submit_done` / :meth:`drain_ready` / the ``busy`` /
    ``has_ready`` / ``backlog`` properties — deliberately the scheduler's
    own programmatic shape, so a caller written against one drives the
    other. Control surface (the ONE router thread): :meth:`pump`, which
    drains the inbox (answers, heartbeats, prefill handoffs, exits),
    sweeps liveness, and dispatches pending requests. :meth:`run` is the
    batch convenience tests and benches use."""

    def __init__(
        self,
        links: "list[ReplicaLink]",
        *,
        encode=None,
        bos_id: int = 1,
        affinity_block: int = 16,
        affinity_slack: int = 4,
        max_redispatch: int = 2,
        heartbeat_timeout_s: float = 0.0,
        breaker_threshold: int = 1,
        breaker_cooldown_s: float = 30.0,
        disaggregate: bool = False,
        telemetry=None,
        supervisor=None,
        scaler=None,
        upgrader=None,
        slos=None,
        ha: bool = False,
        epoch: int = 1,
        ha_heartbeat_s: float = 0.5,
    ):
        if not links:
            raise ValueError("router needs at least one replica link")
        self.links = list(links)
        self.encode = encode          # str -> token ids (affinity hashing
        #                               only; None = least-loaded always)
        self.bos_id = bos_id
        self.affinity_block = affinity_block
        self.affinity_slack = affinity_slack
        self.max_redispatch = max(0, max_redispatch)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.disaggregate = disaggregate
        if disaggregate:
            if not any(l.serves("prefill") for l in links) or not any(
                l.serves("decode") for l in links
            ):
                raise ValueError(
                    "disaggregate mode needs at least one prefill-capable "
                    "and one decode-capable replica"
                )
        # Inbox: the ONE channel from replica reader threads (and fakes)
        # into the router thread — (replica_index, msg) tuples.
        self.inbox: queue.Queue = queue.Queue()
        # Intake state (client threads + router thread, under this lock —
        # the same split the scheduler's submit/drain contract uses).
        self._intake_lock = threading.Lock()
        self._next_order = 0
        self._done: dict[int, dict] = {}
        self._emit_next = 0
        self._pending: deque[_Tracked] = deque()
        # Router-thread-owned tables.
        self._inflight: dict[int, _Tracked] = {}
        # Per-replica breakers: a death/timeout opens the breaker so the
        # dispatcher stops offering traffic; a half-open probe after the
        # cooldown lets a recovered link earn its way back.
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self.breakers = [
            CircuitBreaker(
                f"replica_{l.name}", threshold=breaker_threshold,
                cooldown_s=breaker_cooldown_s,
            )
            for l in links
        ]
        self.stats = {
            "submitted": 0, "dispatched": 0, "redispatched": 0,
            "answered": 0, "failovers": 0, "revivals": 0,
            "duplicate_answers": 0, "expired": 0, "exhausted": 0,
            "no_replica": 0, "prefill_handoffs": 0, "dropped_heartbeats": 0,
        }
        # ---- supervision / autoscaling / HA (serve/supervisor.py,
        # serve/standby.py; docs/SERVING.md "Self-healing fleet") ----------
        self._sup = supervisor
        self._scaler = scaler
        # Live-weights control plane (serve/upgrade.py): the rollout
        # coordinator, and the fleet's TARGET weights — (ckpt_dir,
        # weight_version) once a rollout starts/completes, None before
        # (and after a rollback). The supervisor's spawn recipe reads it
        # so a respawned replacement bootstraps at the version the fleet
        # is converging to, never the stale original argv weights.
        self._upgrader = upgrader
        self.weight_target: "tuple[str, str] | None" = None
        self.ha = ha
        self.epoch = epoch
        self.ha_heartbeat_s = ha_heartbeat_s
        self._last_ha_hb = 0.0
        # The router's OWN SLO engine over the answer funnel: replicas ship
        # per-answer latency in the "slo" side channel, the funnel records
        # it here, and the FleetScaler consumes the burn gauges — the PR 9
        # engine driving fleet size, as the ROADMAP elasticity item asks.
        self._slo_engine = None
        if slos is not None and hasattr(slos, "maybe_evaluate"):
            # A prebuilt SLOEngine (tests pin the clock/interval; the
            # standby hands over its own engine across the cutover).
            self._slo_engine = slos
        elif slos:
            from transformer_tpu.obs.slo import SLOEngine, parse_slo_spec

            specs = (
                parse_slo_spec(slos) if isinstance(slos, str) else tuple(slos)
            )
            if specs:
                self._slo_engine = SLOEngine(
                    specs,
                    registry=(
                        telemetry.registry if telemetry is not None else None
                    ),
                    emit=telemetry.emit if telemetry is not None else None,
                )
        # submit -> first dispatch; bounded (the bench reads it — the
        # serve-forever process must not grow a list per request when the
        # same data lives in the router_queue_seconds histogram).
        self.queue_latencies: "deque[float]" = deque(maxlen=65536)
        self._tel = telemetry
        self._tracer = getattr(telemetry, "tracer", None)
        if telemetry is not None:
            reg = telemetry.registry
            self._m_dispatch = reg.counter(
                "router_dispatch_total", "requests dispatched to replicas")
            self._m_redispatch = reg.counter(
                "router_redispatch_total",
                "failover re-dispatches of in-flight requests")
            self._m_failover = reg.counter(
                "router_failover_total", "replica failures handled")
            self._m_answers = reg.counter(
                "router_answers_total", "replica answers accepted")
            self._m_dup = reg.counter(
                "router_duplicate_answers_total",
                "late/duplicate replica answers dropped by the funnel")
            self._m_queue_s = reg.histogram(
                "router_queue_seconds", "submit -> first dispatch")
            self._m_replicas = reg.gauge(
                "router_replicas_live", "replica links currently usable")
            self._m_replicas.set(len(links))
            self._m_fleet = reg.gauge(
                "route_fleet_size",
                "healthy serving replicas (live, admitted, not draining)")
            self._m_fleet.set(len(links))
        if supervisor is not None:
            supervisor.attach(self)
        if scaler is not None:
            if supervisor is None:
                raise ValueError("a FleetScaler needs a Supervisor to act")
            scaler.bind(self, supervisor)
        if upgrader is not None:
            upgrader.attach(self)

    # ---- client intake (any thread) ---------------------------------------

    def submit(self, req: dict) -> int:
        """Accept one LM request; returns its output order. Affinity and
        trace identity are minted here so failover can re-dispatch with
        both intact."""
        now = time.perf_counter()
        span_root = None
        parent = SpanContext.from_traceparent(req.get("traceparent"))
        if self._tracer is not None:
            span_root = self._tracer.start_span(
                "route.request", parent=parent, lane="router"
            )
            ctx = span_root.ctx
        else:
            ctx = parent.child() if parent is not None else SpanContext.new()
        affinity = None
        if self.encode is not None:
            try:
                ids = [self.bos_id, *self.encode(str(req.get("prompt", "")))]
                affinity = affinity_key(ids, self.affinity_block)
            except Exception:  # tpa: disable=TPA006 — affinity is a routing hint: an unencodable prompt routes least-loaded and the REPLICA answers the validation error (one answer path for bad requests)
                affinity = None
        deadline = None
        try:
            d = req.get("deadline_ms")
            if d is not None:
                deadline = now + float(d) / 1e3
        except (TypeError, ValueError):
            pass  # the replica's admission answers the validation error
        with self._intake_lock:
            order = self._next_order
            self._next_order += 1
            self.stats["submitted"] += 1
            self._pending.append(
                _Tracked(
                    order=order, req=req, ctx=ctx, t_submit=now,
                    deadline=deadline, affinity=affinity,
                    stage="prefill" if self.disaggregate else "decode",
                    span_root=span_root,
                )
            )
        if self.ha:
            # The standby's replayable intake record: enough to re-own (or
            # re-dispatch) this order after adopting the fleet — the
            # request itself, its trace identity, and its remaining
            # deadline budget (serve/standby.py).
            self.emit_event(
                "route.intake", order=order, req=req,
                traceparent=ctx.to_traceparent(),
                deadline_ms=(
                    None if deadline is None
                    else round((deadline - now) * 1e3, 3)
                ),
            )
        return order

    def submit_done(self, resp: dict) -> int:
        """Reserve an output position for an already-answered response
        (parse/routing errors) — ordering is preserved across both."""
        with self._intake_lock:
            order = self._next_order
            self._next_order += 1
            self.stats["submitted"] += 1
            self._done[order] = resp
        if self.ha:
            # Pre-answered orders carry their response in the intake
            # record: the standby re-answers them from the log alone.
            self.emit_event("route.intake", order=order, resp=resp)
        if self._tracer is not None:
            span = self._tracer.start_span("route.request", lane="router")
            extra = {}
            if "error" in resp:
                extra = {"error": resp["error"]}
                if "code" in resp:
                    extra["code"] = resp["code"]
            span.end(order=order, **extra)
        return order

    def drain_ready(self) -> list[dict]:
        """Responses completed in arrival order (the stdout contract)."""
        out = []
        with self._intake_lock:
            first = self._emit_next
            while self._emit_next in self._done:
                out.append(self._done.pop(self._emit_next))
                self._emit_next += 1
            last = self._emit_next
        if self.ha and out:
            # Delivery marks, not completion marks: an answer sitting
            # out-of-order in _done died with this process — the standby
            # recovers it from the replicas' re-delivery caches, while
            # DELIVERED orders must never reach the client twice.
            self.emit_event(
                "route.answered", first=first, upto=last - 1, n=len(out)
            )
        return out

    @property
    def busy(self) -> bool:
        with self._intake_lock:
            return self._emit_next < self._next_order

    @property
    def has_ready(self) -> bool:
        with self._intake_lock:
            return self._emit_next in self._done

    @property
    def backlog(self) -> int:
        """Accepted-but-unanswered requests (pending + in flight)."""
        with self._intake_lock:
            return (self._next_order - self._emit_next) - len(self._done)

    # ---- the router thread -------------------------------------------------

    def pump(self, timeout: float = 0.05) -> bool:
        """One control-loop turn: drain the inbox (blocking up to
        ``timeout`` only when there is nothing to dispatch), sweep replica
        liveness, dispatch pending requests. Returns whether any message
        or dispatch happened (the idle signal for callers)."""
        progressed = self._drain_inbox(timeout)
        self._sweep_liveness()
        progressed |= self._dispatch_pending()
        # Supervision tier (serve/supervisor.py): advance respawn/warm
        # state machines, ship shutdowns to drained retirees, then let the
        # scaling policy consume the freshest SLO burn evaluation.
        if self._sup is not None:
            progressed |= self._sup.poll()
            progressed |= self._sup.reap_draining()
        if self._upgrader is not None:
            progressed |= self._upgrader.poll()
        slo_result = None
        if self._slo_engine is not None:
            slo_result = self._slo_engine.maybe_evaluate()
        if self._scaler is not None:
            progressed |= self._scaler.poll(slo_result)
        if self.ha:
            self._ha_heartbeat()
        return progressed

    def run(self, reqs: "list[dict]") -> "list[dict]":
        """Submit ``reqs`` and pump until every one is answered; responses
        in request order (the scheduler-``run`` convenience)."""
        for req in reqs:
            self.submit(req)
        out: list[dict] = []
        while self.busy:
            self.pump()
            out.extend(self.drain_ready())
        out.extend(self.drain_ready())
        if self._tel is not None:
            self._tel.maybe_flush(force=True)
        return out

    def shutdown(self) -> None:
        """Close every replica link (graceful drain where the transport
        supports it) and flush telemetry."""
        for link in self.links:
            link.close()
        if self._tel is not None:
            self._tel.maybe_flush(force=True)

    # -- fleet management (serve/supervisor.py, router thread) ---------------

    def emit_event(self, kind: str, **fields) -> None:
        """Telemetry-gated event emission — the supervision tier's one
        outlet (``route.spawn`` / ``route.retire`` / ``route.scale`` / ...),
        shared so fakes in tests can observe through a real EventLog."""
        if self._tel is not None:
            self._tel.emit(kind, **fields)

    def replace_link(self, index: int, link: ReplicaLink) -> None:
        """Swap a respawned replacement in UNDER ITS PREDECESSOR'S index
        and name — rendezvous hashing therefore re-offers it exactly the
        affinity keys the dead replica owned. The replacement arrives
        ``warming`` (the supervisor admits it after cache warm-up)."""
        self.links[index] = link
        link.last_hb = None
        if hasattr(link, "start_reader"):
            link.start_reader(self.inbox)
        self.on_fleet_change()

    def append_link(self, link: ReplicaLink) -> None:
        """Grow the fleet by one (FleetScaler scale-up): a fresh breaker,
        a fresh rendezvous name — existing keys keep their owners."""
        self.links.append(link)
        self.breakers.append(
            CircuitBreaker(
                f"replica_{link.name}", threshold=self._breaker_threshold,
                cooldown_s=self._breaker_cooldown_s,
            )
        )
        if hasattr(link, "start_reader"):
            link.start_reader(self.inbox)
        self.on_fleet_change()

    def start_upgrade(self, ckpt: str) -> dict:
        """Begin a rolling weight swap to ``ckpt`` (the ``--upgrade`` flag
        and the control-line command both land here). Returns the
        coordinator's status dict; a router without an UpgradeCoordinator
        answers a structured refusal instead of raising."""
        if self._upgrader is None:
            return {
                "ok": False, "code": "upgrade",
                "error": "this router has no UpgradeCoordinator attached",
            }
        return self._upgrader.start(ckpt)

    def reset_breaker(self, index: int) -> None:
        """A freshly admitted REPLACEMENT process deserves a fresh breaker:
        the old one's open state belongs to the dead process (an OPEN
        breaker deliberately ignores stray successes, so re-arming must be
        explicit, not a side effect of the first answer)."""
        link = self.links[index]
        self.breakers[index] = CircuitBreaker(
            f"replica_{link.name}", threshold=self._breaker_threshold,
            cooldown_s=self._breaker_cooldown_s,
        )

    @property
    def healthy_links(self) -> "list[ReplicaLink]":
        """Links currently SERVING: live, admitted, not draining — the one
        definition of fleet size the gauge, the autoscaler, and the warm-
        source picker all share."""
        return [
            l for l in self.links
            if not l.dead and not l.warming and not l.draining
            and not l.upgrading
        ]

    def on_fleet_change(self) -> None:
        """Refresh the fleet-size gauges after any membership change."""
        if self._tel is not None:
            self._m_replicas.set(sum(1 for l in self.links if not l.dead))
            self._m_fleet.set(len(self.healthy_links))

    def seed_takeover(
        self,
        *,
        next_order: int,
        emit_next: int,
        done: "dict[int, dict]",
        inflight: "list[tuple[int, _Tracked]]",
        pending: "list[_Tracked]",
    ) -> None:
        """Install adopted state from a warm standby's takeover
        (``serve/standby.py``): the order clock resumes past every order
        the primary minted, delivery resumes at the client's floor
        (``emit_next``), recovered answers land in the funnel, replica-
        claimed orders are re-owned in the in-flight table exactly once,
        and unknowns queue for dispatch. Call BEFORE the pump thread
        starts — this is takeover bootstrap, not a concurrent surface."""
        with self._intake_lock:
            self._next_order = max(self._next_order, next_order)
            self._emit_next = emit_next
            self._done.update(done)
            self._pending.extend(pending)
        for index, rr in inflight:
            rr.replica = index
            self._inflight[rr.order] = rr
            self.links[index].inflight += 1
        if self.ha:
            # Re-journal the adopted state: THIS router's journal starts
            # empty, and route.intake is otherwise only written by
            # submit()/submit_done() — without these records a SECOND
            # (chained) standby tailing us would neither re-own nor
            # re-dispatch the adopted orders and its funnel would wedge
            # at the delivery floor forever.
            if emit_next > 0:
                # Floor mark (n=0): nothing newly delivered, but orders
                # below emit_next reached the client via a predecessor.
                self.emit_event(
                    "route.answered", first=emit_next, upto=emit_next - 1,
                    n=0,
                )
            now = time.perf_counter()
            for order in sorted(done):
                self.emit_event("route.intake", order=order,
                                resp=done[order])
            for rr in sorted(
                [rr for _, rr in inflight] + list(pending),
                key=lambda r: r.order,
            ):
                self.emit_event(
                    "route.intake", order=rr.order, req=rr.req,
                    traceparent=rr.ctx.to_traceparent(),
                    deadline_ms=(
                        None if rr.deadline is None
                        else round((rr.deadline - now) * 1e3, 3)
                    ),
                )

    def _ha_heartbeat(self) -> None:
        """The primary's liveness beacon for a warm standby
        (``serve/standby.py``): a periodic ``route.hb`` event on the
        answer-funnel event log carrying the authority epoch and the
        replica control ports. The order-keyed inflight table itself is
        NOT in the beacon — the standby reconstructs it from the
        ``route.intake``/``route.answered`` records, so the beacon stays
        O(fleet) on the pump hot path instead of O(inflight) twice a
        second."""
        now = time.monotonic()
        if now - self._last_ha_hb < self.ha_heartbeat_s:
            return
        self._last_ha_hb = now
        self.emit_event(
            "route.hb",
            epoch=self.epoch,
            ports={
                l.name: l.control_port
                for l in self.links
                if l.control_port is not None and not l.retired
            },
        )

    # -- inbox --------------------------------------------------------------

    def _drain_inbox(self, timeout: float) -> bool:
        with self._intake_lock:
            idle = not self._pending
        try:
            if idle and timeout > 0:
                item = self.inbox.get(timeout=timeout)
            else:
                item = self.inbox.get_nowait()
        except queue.Empty:
            return False
        handled = 0
        while True:
            self._handle_msg(*item)
            handled += 1
            try:
                item = self.inbox.get_nowait()
            except queue.Empty:
                break
        return handled > 0

    def _handle_msg(self, index: int, msg: dict) -> None:
        link = self.links[index]
        kind = msg.get("type")
        if kind == "answer":
            self._on_answer(link, msg)
        elif kind == "hb":
            try:
                # route.hb fault point: deterministically SWALLOW replica
                # heartbeats so --fault_spec episodes drill heartbeat-loss
                # failover storms without real stalls (docs/ROBUSTNESS.md).
                maybe_fail("route.hb")
            except InjectedFault:
                self.stats["dropped_heartbeats"] += 1
                return
            link.last_hb = time.monotonic()
            link.hb_backlog = int(msg.get("backlog", 0))
            link.hb_free = int(msg.get("free", 0))
            link.hb_active = int(msg.get("active", 0))
            if msg.get("wv") is not None:
                link.wv = msg["wv"]
            if msg.get("mesh") is not None:
                link.mesh = msg["mesh"]
        elif kind == "prefilled":
            self._on_prefilled(link, msg)
        elif kind == "exit":
            # A supervisor-respawned REPLACEMENT at this index must never
            # be failed over by its predecessor's EOF sentinel: the old
            # reader thread can outlive the link swap, so the sentinel's
            # pid must match the CURRENT process behind the link.
            pid = msg.get("pid")
            cur = getattr(link, "pid", None)
            cur = cur() if callable(cur) else None
            if pid is not None and cur is not None and pid != cur:
                return
            if not link.dead:
                self._fail_replica(index, "pipe closed")
        elif kind == "ready":
            link.last_hb = time.monotonic()
            port = msg.get("control_port")
            if isinstance(port, int):
                link.control_port = port
            if msg.get("weight_version") is not None:
                # A replica bootstrapped from --init_ckpt announces the
                # verified version it serves — a respawn mid-rollout comes
                # up already converged to the fleet's target.
                link.wv = msg["weight_version"]
            if msg.get("mesh") is not None:
                # Captured BEFORE on_ready: the supervisor's wrong-shape
                # refusal judges the replica's announced mesh.
                link.mesh = msg["mesh"]
            if self._sup is not None and link.warming:
                self._sup.on_ready(link)
        elif kind in ("upgrade_staged", "upgraded"):
            if kind == "upgraded" and msg.get("ok", True):
                link.wv = msg.get("version")
            if self._upgrader is not None:
                self._upgrader.on_msg(link, msg)
        elif kind == "prefix_state":
            if self._sup is not None:
                self._sup.on_prefix_state(link, msg)
        elif kind == "state_injected":
            if self._sup is not None:
                self._sup.on_state_injected(link, msg)
        elif kind == "stats":
            link.final_stats = msg.get("stats")  # bench introspection
            link.final_perf = msg.get("perf")    # profiler rows (ditto)
        elif kind == "flight":
            # A `dump` reply: hold the freshest wire-shipped flight record
            # for the Supervisor's postmortem capture.
            link.flight_record = msg.get("record")

    def _on_answer(self, link: ReplicaLink, msg: dict) -> None:
        order = msg.get("rid")
        rr = self._inflight.pop(order, None)
        if rr is None:
            # The order-keyed answer funnel's at-most-once arm: already
            # answered (a failover raced a completing replica), already
            # drained, or never ours — count and drop.
            self.stats["duplicate_answers"] += 1
            if self._tel is not None:
                self._m_dup.inc()
            return
        # Unload the replica the order is CURRENTLY assigned to, not the
        # answering one: a failed-over victim's late answer must release
        # the survivor's slot (the survivor's own answer for this order
        # takes the duplicate early-return above and never decrements).
        assigned = self.links[rr.replica] if rr.replica is not None else link
        assigned.inflight = max(0, assigned.inflight - 1)
        link.answered += 1
        resp = msg.get("resp")
        if not isinstance(resp, dict):
            resp = error_answer(
                "internal", f"replica {link.name} returned a malformed answer"
            )
        self._answer(rr, resp, replica=link.name, slo=msg.get("slo"))
        self.breakers[link.index].record_success()

    def _on_prefilled(self, link: ReplicaLink, msg: dict) -> None:
        """Disaggregation stage 1 complete: the prefill replica handed the
        prompt's KV blocks back; forward the request (plus blocks) to a
        decode replica."""
        order = msg.get("rid")
        rr = self._inflight.pop(order, None)
        if rr is None:
            self.stats["duplicate_answers"] += 1
            return
        assigned = self.links[rr.replica] if rr.replica is not None else link
        assigned.inflight = max(0, assigned.inflight - 1)
        self.breakers[link.index].record_success()
        rr.stage = "decode"
        rr.replica = None
        rr.blocks = msg.get("blocks")
        rr.blocks_tokens = int(msg.get("tokens", 0))
        self.stats["prefill_handoffs"] += 1
        with self._intake_lock:
            self._pending.appendleft(rr)

    # -- liveness + failover -------------------------------------------------

    def _sweep_liveness(self) -> None:
        now = time.monotonic()
        for link in self.links:
            if link.dead:
                self._maybe_revive(link)
                continue
            if not link.alive():
                self._fail_replica(link.index, "process exited")
            elif (
                self.heartbeat_timeout_s > 0
                and link.last_hb is not None
                and now - link.last_hb > self.heartbeat_timeout_s
            ):
                self._fail_replica(link.index, "heartbeat timeout")

    def _maybe_revive(self, link: ReplicaLink) -> None:
        """The breaker's half-open arm: a failed-over link whose worker
        PROCESS still runs (heartbeat-timeout victims — exited workers
        fail ``alive()`` forever) is re-admitted once a heartbeat NEWER
        than the death mark arrives and the breaker cooldown has elapsed;
        its first answered request then closes the breaker, and a fresh
        failure (half-open -> open) restarts the cooldown."""
        if link.retired or not link.alive():
            return
        if (
            link.last_hb is None
            or link.died_at is None
            or link.last_hb <= link.died_at
        ):
            return
        if not self.breakers[link.index].allow():
            return
        link.dead = False
        link.died_at = None
        self.stats["revivals"] += 1
        self.on_fleet_change()
        self.emit_event("route.revive", replica=link.name)
        # A revival also wins the race against a scheduled respawn: the
        # supervisor's slot returns to "up" on its next poll (link.dead is
        # False again before the backoff elapses).

    def _fail_replica(self, index: int, reason: str) -> None:
        """Zero-loss failover: every in-flight request assigned to the
        dead replica is re-enqueued at the FRONT of the pending queue in
        its original order, with its original trace id and deadline
        intact. The answer funnel keeps at-most-once: if the victim
        replica's answer for one of these orders still arrives (it was
        written before the death), whichever of answer/redispatch lands
        first wins and the other is dropped/cancelled by the funnel."""
        link = self.links[index]
        if link.retired:
            return  # a drained retiree's EOF is not a failure
        link.dead = True
        link.died_at = time.monotonic()
        self.breakers[index].record_failure()
        victims = sorted(
            (rr for rr in self._inflight.values() if rr.replica == index),
            key=lambda rr: rr.order,
        )
        for rr in victims:
            del self._inflight[rr.order]
            rr.replica = None
            rr.refailed = True  # the next dispatch is a bounded redispatch
            if self.disaggregate and rr.stage == "prefill":
                rr.blocks = None  # the handoff payload died with the worker
        link.inflight = 0
        with self._intake_lock:
            self._pending.extendleft(reversed(victims))
        self.stats["failovers"] += 1
        if self._tel is not None:
            self._m_failover.inc()
        self.on_fleet_change()
        self.emit_event(
            "route.failover",
            replica=link.name,
            reason=reason,
            orders=[rr.order for rr in victims],
            traces=[rr.ctx.trace_id for rr in victims],
        )
        if self._sup is not None:
            self._sup.on_death(link)
        if self._upgrader is not None:
            self._upgrader.on_death(link)

    # -- dispatch ------------------------------------------------------------

    def _usable(self, stage: str) -> "list[ReplicaLink]":
        out = []
        for link in self.links:
            if link.dead or not link.serves(stage):
                continue
            if link.warming or link.draining or link.upgrading:
                # Supervision states: a warming replacement is still
                # bootstrapping/cache-warming; a draining retiree finishes
                # its in-flight work but takes nothing new; an upgrading
                # replica is quiescing for (or mid-) a weight swap.
                continue
            if not self.breakers[link.index].allow():
                continue
            out.append(link)
        return out

    def _load(self, link: ReplicaLink) -> int:
        return link.inflight + link.hb_backlog

    def _pick(self, rr: _Tracked) -> "tuple[ReplicaLink, str] | None":
        stage = rr.stage if self.disaggregate else "decode"
        usable = self._usable(stage)
        if not usable and self.disaggregate and stage == "prefill":
            # Degradation: no prefill worker left — decode replicas can
            # serve the whole request (full prefill), losing only the
            # handoff win, never the request.
            rr.stage = "decode"
            rr.blocks = None
            usable = self._usable("decode")
        elif not usable and self.disaggregate and stage == "decode":
            # Mirror degradation: no decode-capable replica left — a live
            # prefill-only worker runs the same scheduler and serves the
            # whole request (rr.stage stays "decode", so the forwarded
            # message is a full "req"); role segregation yields before
            # zero-loss does.
            usable = self._usable("prefill")
        if not usable:
            return None
        if self._upgrader is not None:
            # Canary pinning: during a rollout's canary window, a
            # deterministic slice of accepted orders routes to the first
            # upgraded replica so the per-version SLO split has traffic
            # to judge (serve/upgrade.py).
            forced = self._upgrader.route(rr, usable)
            if forced is not None:
                return forced, "canary"
        least = min(usable, key=lambda l: (self._load(l), l.index))
        if rr.affinity is None:
            return least, "least_loaded"
        affine = max(usable, key=lambda l: _rendezvous(rr.affinity, l.name))
        if self._load(affine) - self._load(least) > self.affinity_slack:
            return least, "least_loaded"
        return affine, "affinity"

    def _dispatch_pending(self) -> bool:
        progressed = False
        while True:
            with self._intake_lock:
                if not self._pending:
                    return progressed
                rr = self._pending.popleft()
            now = time.perf_counter()
            if rr.deadline is not None and now >= rr.deadline:
                self.stats["expired"] += 1
                self._answer(
                    rr,
                    error_answer(
                        "deadline",
                        "deadline_ms elapsed in the router queue after "
                        f"{round((now - rr.t_submit) * 1e3)}ms",
                    ),
                )
                progressed = True
                continue
            if rr.refailed and rr.redispatches >= self.max_redispatch:
                self.stats["exhausted"] += 1
                self._answer(
                    rr,
                    error_answer(
                        "transient",
                        f"request redispatched {self.max_redispatch} time(s) "
                        "after replica failures and still unanswered",
                    ),
                )
                progressed = True
                continue
            picked = self._pick(rr)
            if picked is None:
                if any(not l.dead for l in self.links):
                    # Breakers half-open/cooling: park the request at the
                    # front and let the next pump retry.
                    with self._intake_lock:
                        self._pending.appendleft(rr)
                    return progressed
                self.stats["no_replica"] += 1
                self._answer(
                    rr,
                    error_answer(
                        "transient",
                        "no live replica to serve the request (all "
                        f"{len(self.links)} failed)",
                    ),
                )
                progressed = True
                continue
            link, policy = picked
            fwd = dict(rr.req)
            fwd["traceparent"] = rr.ctx.to_traceparent()
            if rr.deadline is not None:
                fwd["deadline_ms"] = max(
                    0.0, round((rr.deadline - now) * 1e3, 3)
                )
            msg = {"type": "req", "rid": rr.order, "req": fwd}
            if self.disaggregate and rr.stage == "prefill":
                msg["type"] = "prefill"
            elif rr.blocks is not None:
                msg["blocks"] = rr.blocks
                msg["tokens"] = rr.blocks_tokens
            try:
                link.send(msg)
            except (OSError, ValueError):  # tpa: disable=TPA007 — bounded: _fail_replica permanently removes the dead link (at most N send failures total) and rr.attempts is capped by max_redispatch above
                with self._intake_lock:
                    self._pending.appendleft(rr)
                self._fail_replica(link.index, "send failed")
                progressed = True
                continue
            # Only failover-driven re-dispatches count against the
            # max_redispatch budget and the redispatch metrics — the
            # disaggregated prefill->decode stage progression is normal
            # request flow, not a failure.
            redispatch = rr.refailed
            rr.refailed = False
            rr.attempts += 1
            if redispatch:
                rr.redispatches += 1
            rr.replica = link.index
            if rr.t_dispatch is None:
                rr.t_dispatch = now
                self.queue_latencies.append(now - rr.t_submit)
            self._inflight[rr.order] = rr
            link.inflight += 1
            link.dispatched += 1
            self.stats["dispatched"] += 1
            if redispatch:
                self.stats["redispatched"] += 1
            progressed = True
            if self._tel is not None:
                self._m_dispatch.inc()
                if redispatch:
                    self._m_redispatch.inc()
                self._m_queue_s.observe(now - rr.t_submit)
                self._tel.emit(
                    "route.dispatch",
                    order=rr.order, replica=link.name, policy=policy,
                    stage=rr.stage if self.disaggregate else None,
                    redispatch=rr.redispatches,
                    weight_version=link.wv,
                    trace=rr.ctx.trace_id,
                )

    # -- the answer funnel ---------------------------------------------------

    def _answer(
        self, rr: _Tracked, resp: dict, replica: str = "", slo=None
    ) -> None:
        with self._intake_lock:
            self._done[rr.order] = resp
        self.stats["answered"] += 1
        if self._upgrader is not None:
            # The per-weight_version SLO split the canary verdict reads —
            # fed from the SAME funnel as the fleet engine below.
            self._upgrader.observe(rr, resp, slo)
        if self._slo_engine is not None:
            # The router's own SLO engine over the answer funnel: the
            # replica's per-answer side channel carries ttft/prefix numbers
            # (serve/replica.py "slo"); router-local answers (queue
            # deadline, redispatch exhaustion, no-replica) contribute their
            # availability/deadline weight with no latency sample. This is
            # the FleetScaler's autoscaling signal.
            sample = dict(slo) if isinstance(slo, dict) else {}
            sample["order"] = rr.order
            sample.setdefault(
                "total_s", round(time.perf_counter() - rr.t_submit, 6)
            )
            if "error" in resp:
                sample["error"] = resp["error"]
                if "code" in resp:
                    sample["code"] = resp["code"]
            self._slo_engine.record(sample)
        if rr.span_root is not None:
            extra = {}
            if "error" in resp:
                extra["error"] = resp["error"]
                if "code" in resp:
                    extra["code"] = resp["code"]
            rr.span_root.end(
                order=rr.order, replica=replica,
                redispatches=rr.redispatches, **extra,
            )
            rr.span_root = None
        if self._tel is not None:
            self._m_answers.inc()
