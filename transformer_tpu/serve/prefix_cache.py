"""Cross-request prefix KV cache: radix-trie prompt reuse for the slot pool.

Real LM serving traffic is dominated by SHARED PREFIXES — a system prompt
every request carries, few-shot templates, retry storms replaying the same
context. The paper's decoder pays a full prefill forward for every one of
those prompts, recomputing K/V the pool computed seconds ago for an
identical token sequence. The KV cache is the object that makes decode
cheap ("Fast Transformer Decoding", Shazeer, arXiv:1911.02150); this module
extends that economy ACROSS requests, in the Mesh-TensorFlow spirit
(PAPERS.md) of restructuring *what* is computed: tokens whose KV already
exists are never re-forwarded.

Mechanics:

- **Blocks.** Completed prefill KV is stored on the HOST as fixed-size,
  token-aligned blocks (``block_tokens`` positions each), per decoder
  layer, in the cache's OWN storage layout (bf16 rows as bf16, int8 codes
  with their fp32 scales, GQA at the kv-head count) — sliced out by
  ``ops.attention.slice_kv_blocks`` and restored by ``insert_kv_blocks``,
  so a restore is bit-identical to the donor's original write and greedy
  answers are byte-identical cache on/off.
- **Radix trie over token ids.** Blocks are indexed by a trie whose edges
  are ``block_tokens``-wide token tuples: a node at depth ``d`` holds the
  KV block for positions ``[d*B, (d+1)*B)`` of every prompt that shares
  that exact token prefix. Matching is a root walk — the longest
  block-aligned shared prefix falls out in O(prefix/B) dict hops, and two
  prompts share storage for exactly the blocks their token ids agree on.
- **Admission.** ``ContinuousScheduler._start`` matches the new prompt,
  copies the matched blocks into the slot's device cache (one
  ``device_put`` + ``dynamic_update_slice`` program — NO model forward),
  and chunk-prefills only the unmatched suffix. Matched widths are padded
  to power-of-two block counts so the restore program compiles
  O(log(max_total / B)) times total, never per hit length (pinned by
  ``analysis.retrace.prefix_cache_retrace_report``).
- **Retirement.** The retiring slot's prompt-region KV (positions
  ``[0, floor(prompt_len / B) * B)``) is sliced into blocks and inserted —
  only blocks the trie does not already hold are fetched off the device.
- **Eviction.** Refcounted LRU under a byte budget (``--prefix_cache_mb``):
  blocks pinned by an in-progress admission are never evicted, and only
  childless nodes are candidates (evicting an interior node would orphan
  its descendants — a trie walk could never reach them again).

**Device-resident tier** (paged serving, ``--kv_layout paged`` —
docs/SERVING.md "Paged KV memory"): when the scheduler attaches its
block-pool allocator (``attach_device_pool``), trie nodes may hold a
refcounted DEVICE block id instead of (or alongside) host bytes. A
retiring slot donates its prompt blocks by reference
(``insert_device`` — no device read, no host copy) and a later hit
restores by block-table ALIASING (``PrefixHit.paged_plan``) — zero
model forwards and zero host<->device copies. Pool pressure spills LRU
device blocks back to the host tier in the SAME host block format
(``release_device_blocks``), so the wire/spill surface — disaggregated
KV handoff, supervisor cache warming (``host_blocks_for``) — is
unchanged. Host-tier hits pay one batched device write and are
re-adopted (``adopt_device``), so the next hit aliases.

Rolling-window caches are refused at construction (same policy as
speculative rollback): a rolling buffer stores position ``p`` at slot
``p % buf_len`` and evicts on wrap, so absolute-position block rows are
neither stable nor complete. Everything else composes: chunked prefill
(the suffix path IS chunked prefill), int8/GQA layouts (blocks store the
layout verbatim), speculative decoding (restore only touches the prompt
region; speculation only writes past it), per-request opt-out
(``"cache_prefix": false`` neither reads nor feeds the cache).

Threading contract (machine-checked: the TPA1xx concurrency rules lint
this module, and ``analysis/schedules.py prefix_cache_contention`` hammers
match/insert/release/evict from two deterministic threads): ONE
``threading.Lock`` (``self._lock``) guards every trie mutation — match,
insert, eviction, refcount pin/release, and the byte/stats accounting.
Today's scheduler drives the cache from a single thread, so the lock is
uncontended noise-level overhead (one uncontended acquire per admission /
retirement, far off the jitted hot path); it exists so the ROADMAP's
multi-replica router can share one cache across serving threads without a
redesign. ``read_block`` (the device fetch) is deliberately called OUTSIDE
the lock — holding the cache lock across a device->host copy would be
exactly the TPA105 blocking-under-lock bug the analysis flags.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from typing import Callable, Sequence

import numpy as np

from transformer_tpu.config import ModelConfig
from transformer_tpu.serve.resilience import fired, maybe_fail


class PrefixCorruptionError(RuntimeError):
    """A stored KV block failed its checksum at match time. The corrupt
    subtree has already been dropped and every pin taken by the failing
    match released — the caller (scheduler admission) records a
    prefix-cache breaker failure and serves the request by full prefill,
    so a flipped bit degrades throughput, never answers."""


def _block_crc(blocks: list[dict[str, np.ndarray]]) -> int:
    """crc32 over one block's buffers in a deterministic (layer, key)
    order — the integrity tag that turns silent KV corruption (bit rot, a
    bad DMA, the ``prefix.corrupt`` chaos point) into a detected fault."""
    crc = 0
    for layer in blocks:
        for key in sorted(layer):
            crc = zlib.crc32(np.ascontiguousarray(layer[key]).tobytes(), crc)
    return crc


class _Node:
    """One trie node = one KV block: per-layer buffer rows for the
    ``block_tokens`` positions this node's depth covers, for every prompt
    sharing the root-to-here token path. With the device tier attached
    (paged serving), a node may instead (or additionally) hold
    ``device_block`` — a refcounted id into the serving pool's
    device-resident block pool (``kernels/kv_pool.py``); hits on such
    nodes restore by block-table aliasing with zero host<->device
    copies, and the host ``blocks`` form is materialized lazily on spill
    or wire export."""

    __slots__ = (
        "children", "parent", "edge", "blocks", "nbytes", "last_used",
        "refs", "crc", "device_block",
    )

    def __init__(self, parent: "_Node | None", edge: tuple[int, ...]):
        self.children: dict[tuple[int, ...], _Node] = {}
        self.parent = parent
        self.edge = edge
        self.blocks: list[dict[str, np.ndarray]] | None = None  # None = root
        self.nbytes = 0
        self.last_used = 0
        self.refs = 0
        self.crc = 0
        self.device_block: int | None = None


@dataclasses.dataclass
class PrefixHit:
    """A pinned match: ``tokens`` block-aligned prefix positions whose KV
    the trie holds. The matched nodes stay refcounted (eviction-proof)
    until ``release()`` — the scheduler releases right after the restore
    program is dispatched."""

    tokens: int
    _nodes: list[_Node]
    _cache: "PrefixCache"

    def stacked(self, cap_tokens: int) -> list[dict[str, np.ndarray]] | None:
        """Matched blocks concatenated along the position axis and padded to
        a POWER-OF-TWO block count (clamped to ``cap_tokens``, the slot
        buffer length) — the static width that keeps the jitted restore
        program's compile set O(log(max_total / block)) instead of one per
        distinct hit length. Pad rows are zeros: they land at positions
        ``>= tokens``, which the offset causal mask already hides and the
        suffix prefill overwrites in place.

        Runs WITHOUT the cache lock: the nodes are pinned (``match``
        refcounted them under the lock), pinned nodes cannot be evicted,
        and ``blocks`` is immutable once attached — so the big numpy
        concatenation never stalls other threads' admissions."""
        if not self._nodes:
            return None
        B = self._cache.block_tokens
        blocks = len(self._nodes)
        padded = 1
        while padded < blocks:
            padded *= 2
        width = min(padded * B, cap_tokens)
        out: list[dict[str, np.ndarray]] = []
        for layer in range(len(self._nodes[0].blocks)):
            per_key: dict[str, np.ndarray] = {}
            for key in self._nodes[0].blocks[layer]:
                parts = [n.blocks[layer][key] for n in self._nodes]
                if width > blocks * B:
                    shape = list(parts[0].shape)
                    shape[1] = width - blocks * B
                    parts.append(np.zeros(shape, dtype=parts[0].dtype))
                per_key[key] = np.concatenate(parts, axis=1)
            out.append(per_key)
        return out

    def paged_plan(self) -> "list[tuple[_Node, int | None, list | None]]":
        """Per matched node, the paged restore source: ``(node,
        device_block_id, host_blocks)`` — alias the device block when one
        exists (zero copies), else scatter-write the host payload into a
        fresh pool block (the scheduler then re-adopts it via
        :meth:`PrefixCache.adopt_device`, so the NEXT hit aliases). Safe
        without the lock: the nodes are pinned, pinned nodes are never
        spilled (``release_device_blocks`` skips them) or evicted, and
        both payload forms are immutable while attached."""
        return [(n, n.device_block, n.blocks) for n in self._nodes]

    def release(self) -> None:
        with self._cache._lock:
            for node in self._nodes:
                node.refs -= 1
        self._nodes = []


class PrefixCache:
    """Host-side radix-trie store of prompt-prefix KV blocks.

    ``match``/``insert`` are the whole scheduler-facing surface; both are
    plain host code (numpy + dicts) driven at admission/retirement
    boundaries. ``stats`` is cache-level introspection (block/eviction
    counts); hit-token accounting lives in the SCHEDULER's stats and
    telemetry counters (``serve_prefix_hit_tokens_total``), which count
    only hits whose admission actually succeeded.

    SCOPE: one cache per serving process — blocks are keyed by token ids
    alone, so every scheduler sharing an instance must serve the SAME
    params and cache layout (a serve process has exactly one of each;
    sharing across different weights would silently restore the wrong
    model's K/V)."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        block_tokens: int = 16,
        budget_mb: int = 64,
        verify_checksums: bool = True,
    ):
        if cfg.attention_window:
            raise ValueError(
                "prefix cache cannot serve a rolling-window cache "
                "(attention_window): block restore addresses buffer rows by "
                "absolute position, which a rolling buffer evicts on wrap — "
                "the same policy that refuses speculative rollback"
            )
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        if budget_mb < 1:
            raise ValueError(f"budget_mb must be >= 1, got {budget_mb}")
        self.cfg = cfg
        self.block_tokens = block_tokens
        self.budget_bytes = budget_mb * (1 << 20)
        self.verify_checksums = verify_checksums
        # THE threading contract: one lock for every trie mutation (match,
        # insert, evict, pin/release) and the byte/stats accounting. The
        # schedule checker's prefix_cache_contention scenario explores
        # two-thread interleavings against exactly this guard.
        self._lock = threading.Lock()
        self._root = _Node(None, ())
        self._clock = 0
        self._bytes = 0
        self._bytes_per_block = 0  # learned from the first inserted block
        # Device-resident tier (paged serving): the pool allocator whose
        # refcounts device blocks live under, and the reader that fetches
        # one block to host format (spill / wire export). Attached by the
        # scheduler via attach_device_pool; None = host-only (dense).
        self._pool = None
        self._device_reader = None
        self.stats = {
            "blocks": 0,
            "inserted_blocks": 0,
            "evicted_blocks": 0,
            "corrupt_blocks": 0,
            "device_blocks": 0,
            "spilled_blocks": 0,
        }

    # ---- device-resident tier (paged serving) -----------------------------

    def attach_device_pool(self, pool, reader) -> None:
        """Enable the device tier: ``pool`` is the serving scheduler's
        ``kernels.kv_pool.KVPool`` (the refcount authority for device
        blocks) and ``reader(block_id)`` fetches one pool block to the
        host block format (used only for spill-under-pressure and wire
        exports — the hit path is pure table aliasing)."""
        with self._lock:
            self._pool = pool
            self._device_reader = reader

    def insert_device(
        self, ids: Sequence[int], n_tokens: int, block_ids: Sequence[int]
    ) -> int:
        """Adopt a retiring slot's device blocks for the first
        ``floor(n_tokens / B) * B`` positions of ``ids``: each missing
        trie node takes a pool reference on its block (``block_ids[j]``)
        — NO device read, NO host copy. Nodes the trie already holds just
        refresh recency (and adopt the device id if they were host-only).
        Returns 0 (the host byte budget is untouched)."""
        maybe_fail("prefix.insert")
        B = self.block_tokens
        with self._lock:
            if self._pool is None:
                raise RuntimeError(
                    "insert_device needs an attached device pool "
                    "(attach_device_pool)"
                )
            self._clock += 1
            node = self._root
            for j in range(n_tokens // B):
                key = tuple(ids[j * B : (j + 1) * B])
                child = node.children.get(key)
                if child is None:
                    child = _Node(node, key)
                    node.children[key] = child
                if child.device_block is None:
                    self._pool.retain(int(block_ids[j]))
                    child.device_block = int(block_ids[j])
                    self.stats["device_blocks"] += 1
                child.last_used = self._clock
                node = child
        return 0

    def adopt_device(self, node: _Node, block_id: int) -> None:
        """Attach a freshly written pool block to a (host-tier) node the
        scheduler just restored through it — the next hit on this node
        aliases instead of paying the host copy again. No-op when the
        node already carries a device block."""
        with self._lock:
            if self._pool is None or node.device_block is not None:
                return
            self._pool.retain(int(block_id))
            node.device_block = int(block_id)
            self.stats["device_blocks"] += 1

    def host_blocks_for(self, node: _Node) -> "list[dict[str, np.ndarray]]":
        """A node's KV payload in host block format: the stored host
        blocks when present, else an EPHEMERAL device read (wire exports
        — ``--disaggregate`` handoff, supervisor cache warming). Caller
        must hold a pin on the node (a live ``PrefixHit``)."""
        if node.blocks is not None:
            return node.blocks
        reader = self._device_reader
        if node.device_block is None or reader is None:
            raise ValueError("node holds neither host nor device blocks")
        return [
            {k: np.asarray(v) for k, v in layer.items()}
            for layer in reader(node.device_block)
        ]

    def release_device_blocks(self, want_free: int, spill: bool = True) -> int:
        """Release LRU unpinned device-tier blocks until the pool freed
        ``want_free`` of them (or candidates run out). With ``spill``,
        each block's data is read back to host first and kept under the
        host byte budget when it fits (the wire format — nothing is lost
        unless the host budget is also full). Returns pool blocks
        actually freed (a block still aliased by a live slot releases the
        tier's reference but frees nothing yet)."""
        freed = 0
        while freed < want_free:
            with self._lock:
                victim = None
                stack = [self._root]
                while stack:
                    n = stack.pop()
                    stack.extend(n.children.values())
                    if (
                        n.device_block is not None
                        and n.refs == 0
                        and (victim is None or n.last_used < victim.last_used)
                    ):
                        victim = n
                if victim is None:
                    break
                bid = victim.device_block
                reader = self._device_reader
                pool = self._pool
                need_spill = spill and victim.blocks is None
            host = None
            if need_spill and reader is not None:
                try:
                    # Device read OUTSIDE the lock (TPA105): the victim is
                    # re-checked after reacquiring — a peer that raced us
                    # simply wins.
                    host = [
                        {k: np.asarray(v) for k, v in layer.items()}
                        for layer in reader(bid)
                    ]
                except Exception:  # noqa: BLE001  # tpa: disable=TPA006 — spill is best-effort: an unreadable block is dropped (the tier must still shrink under pool pressure), and the next admission of that prefix simply full-prefills
                    host = None
            with self._lock:
                if victim.device_block != bid or victim.refs:
                    continue  # raced: re-scan
                victim.device_block = None
                self.stats["device_blocks"] -= 1
                if host is not None and victim.blocks is None:
                    nbytes = sum(
                        a.nbytes for layer in host for a in layer.values()
                    )
                    if self._bytes_per_block == 0:
                        self._bytes_per_block = nbytes
                    if self._make_room(nbytes) is not None:
                        victim.blocks = host
                        victim.nbytes = nbytes
                        victim.crc = _block_crc(host)
                        self._bytes += nbytes
                        self.stats["blocks"] += 1
                        self.stats["spilled_blocks"] += 1
                if victim.blocks is None and not victim.children:
                    parent = victim.parent
                    if parent is not None and (
                        parent.children.get(victim.edge) is victim
                    ):
                        del parent.children[victim.edge]
            if pool is not None and pool.release(bid):
                freed += 1
        return freed

    # ---- matching ---------------------------------------------------------

    def match(self, ids: Sequence[int]) -> PrefixHit:
        """Longest block-aligned prefix of ``ids`` the trie holds. Callers
        pass the prompt MINUS its last token (``ids[:L-1]``): at least one
        token must still go through the model forward — the admission pick
        needs next-token logits, and a restore produces none. The matched
        nodes leave pinned (refcounted under the lock), so a concurrent
        insert's eviction can never free blocks the caller is about to
        restore.

        Every matched block's crc32 is re-verified (outside the lock — the
        pins make that safe) before the hit is returned: a corrupt block
        drops its whole subtree and raises :class:`PrefixCorruptionError`
        with zero pins left outstanding, so bit rot in stored KV can never
        be silently restored into a slot. ``verify_checksums=False`` at
        construction trades that guarantee back for the crc pass."""
        maybe_fail("prefix.match")
        B = self.block_tokens
        with self._lock:
            self._clock += 1
            node, nodes = self._root, []
            for j in range(len(ids) // B):
                child = node.children.get(tuple(ids[j * B : (j + 1) * B]))
                if child is None or (
                    # A data-less structural node (its payload was spilled
                    # away and dropped) ends the match: positions past the
                    # hole cannot be restored from either tier.
                    child.blocks is None and child.device_block is None
                ):
                    break
                child.last_used = self._clock
                child.refs += 1
                nodes.append(child)
                node = child
        corrupt_target = next(
            (n for n in nodes if n.blocks is not None), None
        )
        if corrupt_target is not None and fired("prefix.corrupt"):
            # Chaos point: flip one byte of the first matched HOST block's
            # stored buffers — the checksum pass below must catch it
            # (device-tier blocks have no host bytes to flip).
            layer = corrupt_target.blocks[0]
            key = next(iter(sorted(layer)))
            arr = layer[key]
            raw = np.frombuffer(arr.tobytes(), np.uint8).copy()
            raw[0] ^= 0xFF
            layer[key] = np.frombuffer(raw.tobytes(), arr.dtype).reshape(
                arr.shape
            )
        if self.verify_checksums:
            for bad in nodes:
                if bad.blocks is None:
                    continue  # device-resident: no host bytes to verify
                if _block_crc(bad.blocks) == bad.crc:
                    continue
                with self._lock:
                    for n in nodes:
                        n.refs -= 1
                    self.stats["corrupt_blocks"] += 1
                    self._drop_subtree(bad)
                raise PrefixCorruptionError(
                    f"prefix-cache block at depth {nodes.index(bad) + 1} "
                    "failed its checksum; the corrupt subtree was dropped "
                    "(or deferred until a peer's pins release)"
                )
        return PrefixHit(tokens=len(nodes) * B, _nodes=nodes, _cache=self)

    def _drop_subtree(self, node: _Node) -> None:
        """Detach ``node`` (and everything under it — descendants are
        unreachable once their ancestor is gone) after a checksum failure.
        A subtree holding ANY peer pin is left in place instead: a
        mid-insert peer has unlocked to fetch a block and will re-attach
        under this path — detaching it now would let that attach land on an
        unreachable parent, leaking byte-budget accounting forever (the
        exact invariant ``insert``'s descend-path pinning documents). The
        corrupt block stays detectable, so the next unpinned match drops
        it. Idempotent under races: only the thread that actually detaches
        adjusts the byte/stat accounting. Caller holds ``self._lock``."""
        if node.parent is None or node.parent.children.get(node.edge) is not node:
            return  # a peer's verify already dropped it
        stack, subtree = [node], []
        while stack:
            n = stack.pop()
            subtree.append(n)
            stack.extend(n.children.values())
        if any(n.refs for n in subtree):
            return  # pinned by a peer (mid-insert/mid-restore): defer
        del node.parent.children[node.edge]
        for n in subtree:
            if n.blocks is not None:
                self._bytes -= n.nbytes
                self.stats["blocks"] -= 1
            if n.device_block is not None:
                # cache lock -> pool lock is the ONE nesting order
                # (never reversed anywhere), so no lock-order cycle.
                if self._pool is not None:
                    self._pool.release(n.device_block)
                n.device_block = None
                self.stats["device_blocks"] -= 1

    # ---- insertion + eviction --------------------------------------------

    def insert(
        self,
        ids: Sequence[int],
        n_tokens: int,
        read_block: Callable[[int], list[dict[str, np.ndarray]]],
    ) -> int:
        """Store the first ``floor(n_tokens / B) * B`` positions of ``ids``,
        fetching ONLY the blocks the trie is missing via ``read_block(start)
        -> per-layer host buffers`` (the scheduler's jitted slot slice).
        Evicts LRU unpinned leaves to stay under the byte budget; a block
        that cannot fit (everything else pinned or interior) is dropped,
        never force-stored. Returns the number of blocks evicted.

        The device->host fetch runs OUTSIDE the lock (blocking under a lock
        is the TPA105 bug class); the trie is re-checked after reacquiring,
        so a peer thread that stored the same block first simply wins and
        the duplicate fetch is discarded. The descend path stays pinned
        across the unlock — the parent a new block attaches to can never be
        evicted mid-fetch."""
        maybe_fail("prefix.insert")
        B = self.block_tokens
        node, evicted, pinned = self._root, 0, []
        with self._lock:
            self._clock += 1
        try:
            for j in range(n_tokens // B):
                key = tuple(ids[j * B : (j + 1) * B])
                with self._lock:
                    child = node.children.get(key)
                    if child is not None:
                        # Pin the WHOLE descend path (existing nodes
                        # included): the current node is a childless leaf
                        # right up to the moment its child is attached, so
                        # an unpinned one could be evicted by a peer's
                        # _make_room — and the next block would then hang
                        # off a detached parent, unreachable by any match
                        # yet still counted in the byte budget.
                        child.last_used = self._clock
                        child.refs += 1
                        pinned.append(child)
                        node = child
                        continue
                    if self._bytes_per_block and not self._can_fit(
                        self._bytes_per_block
                    ):
                        break  # budget unreachable: don't even fetch
                blocks = [
                    {k: np.asarray(v) for k, v in layer.items()}
                    for layer in read_block(j * B)
                ]
                nbytes = sum(
                    a.nbytes for layer in blocks for a in layer.values()
                )
                with self._lock:
                    child = node.children.get(key)
                    if child is None:
                        self._bytes_per_block = nbytes
                        freed = self._make_room(nbytes)
                        if freed is None:
                            break  # budget unreachable now: drop the tail
                        evicted += freed
                        child = _Node(node, key)
                        child.blocks = blocks
                        child.nbytes = nbytes
                        child.crc = _block_crc(blocks)
                        node.children[key] = child
                        self._bytes += nbytes
                        self.stats["blocks"] += 1
                        self.stats["inserted_blocks"] += 1
                    child.last_used = self._clock
                    child.refs += 1
                    pinned.append(child)
                    node = child
        finally:
            with self._lock:
                for child in pinned:
                    child.refs -= 1
                self.stats["evicted_blocks"] += evicted
        return evicted

    def _can_fit(self, nbytes: int) -> bool:
        """Whether ``_make_room`` could possibly admit ``nbytes`` more:
        budget headroom plus everything its leaf-first cascade could evict
        (a node is unevictable iff it or ANY descendant is pinned — an
        unpinned chain evicts leaf by leaf). Checked BEFORE fetching a
        block off the device so an unreachable budget never pays the
        device->host copy it is about to drop. Caller holds
        ``self._lock``."""
        if nbytes > self.budget_bytes:
            return False

        def retained(n: _Node) -> int:
            kept = sum(retained(c) for c in n.children.values())
            if kept or n.refs:
                kept += n.nbytes
            return kept

        return retained(self._root) + nbytes <= self.budget_bytes

    def _make_room(self, nbytes: int) -> int | None:
        """Evict LRU unpinned childless nodes until ``nbytes`` more fits
        under the budget. Returns blocks evicted, or None when the budget
        cannot be met (every candidate pinned/interior, or the block alone
        exceeds the whole budget). O(n) scan per eviction — the trie holds
        at most budget/block_bytes nodes, and this runs at retirement
        boundaries, never on the decode hot path. Caller holds
        ``self._lock``."""
        if nbytes > self.budget_bytes:
            return None
        evicted = 0
        while self._bytes + nbytes > self.budget_bytes:
            victim = dev_victim = None
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n.children or n.refs:
                    continue
                if n.blocks is not None:
                    if victim is None or n.last_used < victim.last_used:
                        victim = n
                elif n.device_block is not None:
                    # Device-only leaves free no host bytes; they are
                    # fallback victims only when they structurally block
                    # every host-byte chain from becoming childless.
                    if (
                        dev_victim is None
                        or n.last_used < dev_victim.last_used
                    ):
                        dev_victim = n
            if victim is None:
                victim = dev_victim
            if victim is None:
                return None
            del victim.parent.children[victim.edge]
            if victim.blocks is not None:
                self._bytes -= victim.nbytes
                self.stats["blocks"] -= 1
                evicted += 1
            if victim.device_block is not None:
                if self._pool is not None:
                    self._pool.release(victim.device_block)
                victim.device_block = None
                self.stats["device_blocks"] -= 1
        return evicted

    def hot_prefixes(self, limit: int = 8) -> "list[tuple[int, ...]]":
        """The ``limit`` most-recently-used maximal stored prefixes, as
        token-id tuples (each a whole root-to-leaf block path) — the
        supervisor's warm-from-a-survivor export surface (serve/replica.py
        ``export_state``): injecting a leaf path stores every interior
        block along it, so leaves alone cover the whole trie. Recency is
        the LEAF's ``last_used`` (the same clock eviction consults). Read-
        only under the lock; the actual block payloads are read later via
        :meth:`match`, which re-verifies checksums and pins as usual."""
        leaves: list[tuple[int, tuple[int, ...]]] = []
        with self._lock:
            stack = [(self._root, ())]
            while stack:
                node, path = stack.pop()
                if not node.children and (
                    node.blocks is not None or node.device_block is not None
                ):
                    leaves.append((node.last_used, path))
                for child in node.children.values():
                    stack.append((child, path + child.edge))
        leaves.sort(key=lambda t: -t[0])
        return [path for _, path in leaves[: max(0, limit)]]

    # ---- introspection ----------------------------------------------------

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def block_count(self) -> int:
        with self._lock:
            return self.stats["blocks"]

    def outstanding_refs(self) -> int:
        """Total pins across the trie — 0 whenever no admission is
        mid-restore and no insert is mid-fetch. The chaos suite asserts
        this returns to 0 after every fault storm (a leaked pin would make
        its block immortal under eviction)."""
        with self._lock:
            total, stack = 0, [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                total += n.refs
            return total
