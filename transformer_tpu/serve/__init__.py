"""Serving-side scheduling: continuous (in-flight) batching over a fixed
pool of KV-cache slots (``transformer_tpu/serve/scheduler.py``)."""

from transformer_tpu.serve.scheduler import ContinuousScheduler, SlotPool

__all__ = ["ContinuousScheduler", "SlotPool"]
