"""Serving-side scheduling: continuous (in-flight) batching over a fixed
pool of KV-cache slots (``transformer_tpu/serve/scheduler.py``) and
speculative decoding — draft/verify/rollback on that pool
(``transformer_tpu/serve/speculative.py``)."""

from transformer_tpu.serve.scheduler import ContinuousScheduler, SlotPool
from transformer_tpu.serve.speculative import (
    ModelDrafter,
    NgramDrafter,
    drafter_from_flags,
    speculative_generate,
)

__all__ = [
    "ContinuousScheduler",
    "SlotPool",
    "ModelDrafter",
    "NgramDrafter",
    "drafter_from_flags",
    "speculative_generate",
]
