"""Serving-side scheduling: continuous (in-flight) batching over a fixed
pool of KV-cache slots (``transformer_tpu/serve/scheduler.py``),
speculative decoding — draft/verify/rollback on that pool
(``transformer_tpu/serve/speculative.py``) — and the cross-request prefix
KV cache — radix-trie prompt reuse feeding slot admission
(``transformer_tpu/serve/prefix_cache.py``)."""

from transformer_tpu.serve.prefix_cache import PrefixCache, PrefixHit
from transformer_tpu.serve.scheduler import ContinuousScheduler, SlotPool
from transformer_tpu.serve.speculative import (
    ModelDrafter,
    NgramDrafter,
    drafter_from_flags,
    speculative_generate,
)

__all__ = [
    "ContinuousScheduler",
    "PrefixCache",
    "PrefixHit",
    "SlotPool",
    "ModelDrafter",
    "NgramDrafter",
    "drafter_from_flags",
    "speculative_generate",
]
