"""Serving-side scheduling: continuous (in-flight) batching over a fixed
pool of KV-cache slots (``transformer_tpu/serve/scheduler.py``),
speculative decoding — draft/verify/rollback on that pool
(``transformer_tpu/serve/speculative.py``) — the cross-request prefix
KV cache — radix-trie prompt reuse feeding slot admission
(``transformer_tpu/serve/prefix_cache.py``) — and the fault-tolerance
surface: deterministic fault injection, request deadlines/cancellation,
and the circuit-breaker degradation ladder
(``transformer_tpu/serve/resilience.py``, docs/ROBUSTNESS.md) — plus the
multi-replica serving tier: a prefix-affinity front-end router with
zero-loss failover over replica worker processes
(``transformer_tpu/serve/router.py`` / ``replica.py``,
docs/SERVING.md "Multi-replica router") — and the live-weights control
plane: router-coordinated rolling checkpoint swaps with canary gating and
SLO-driven auto-rollback (``transformer_tpu/serve/upgrade.py``,
docs/SERVING.md "Live-weights rollout")."""

from transformer_tpu.serve.prefix_cache import (
    PrefixCache,
    PrefixCorruptionError,
    PrefixHit,
)
from transformer_tpu.serve.resilience import (
    CircuitBreaker,
    FaultPlane,
    InjectedFault,
    TransientError,
)
from transformer_tpu.serve.router import (
    ReplicaLink,
    ReplicaProcess,
    Router,
)
from transformer_tpu.serve.scheduler import ContinuousScheduler, SlotPool
from transformer_tpu.serve.upgrade import UpgradeCoordinator, UpgradeError
from transformer_tpu.serve.speculative import (
    ModelDrafter,
    NgramDrafter,
    drafter_from_flags,
    speculative_generate,
)

__all__ = [
    "CircuitBreaker",
    "ContinuousScheduler",
    "FaultPlane",
    "InjectedFault",
    "PrefixCache",
    "PrefixCorruptionError",
    "PrefixHit",
    "ReplicaLink",
    "ReplicaProcess",
    "Router",
    "SlotPool",
    "TransientError",
    "UpgradeCoordinator",
    "UpgradeError",
    "ModelDrafter",
    "NgramDrafter",
    "drafter_from_flags",
    "speculative_generate",
]
