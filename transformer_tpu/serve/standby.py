"""Warm-standby router: tail the primary's log, adopt the fleet on death.

The PR 10 router made replicas expendable and left ITSELF the single
point of failure. This module is the HA half of the self-healing tier
(docs/SERVING.md "Router HA"): a :class:`Standby` process tails the
primary router's JSONL event log — the same answer-funnel log ``obs
summarize --merge`` reads — and reconstructs, from three event kinds the
primary emits in ``ha`` mode, everything needed to take over:

- ``route.intake`` — one per accepted order: the request body, its
  W3C traceparent (so the trace survives the cutover), and its remaining
  deadline budget; pre-answered orders (parse errors) carry their
  response inline.
- ``route.answered`` — delivery marks from ``drain_ready``: orders the
  CLIENT has already seen. Completion is not delivery — an answer sitting
  out-of-order in the dead primary's funnel is recovered from the
  replicas, while delivered orders must never reach the client twice.
- ``route.hb`` — the primary's liveness beacon: authority epoch and the
  replica control ports (``serve/replica.py --ha``). The inflight table
  is NOT in the beacon — it is reconstructed from the intake/answered
  records above, which an adopting router re-journals for its own
  successor (``Router.seed_takeover``), so chained takeovers work from
  each primary's log alone.

**Death detection** is heartbeat silence: when no fresh ``route.hb``
event lands for ``takeover_after_s`` (local monotonic clock — file
growth, not event timestamps, so clock skew between the two routers is
irrelevant), the standby declares the primary dead and adopts.

**The takeover handshake** (per replica, over its localhost control
socket)::

    -> {"type": "takeover", "epoch": E+1, "inflight": [order, ...]}
    <- {"type": "adopted", "statuses": {...}, "messages": {...}}

An adopted replica reports every undelivered order as ``done`` (original
answer replayed from its bounded re-delivery cache — an answer that died
in the primary's pipe is recovered here), ``inflight`` (it will answer on
the standby's channel), or ``unknown`` (the standby re-dispatches it).
``rejected`` means a HIGHER epoch already owns the worker — another
standby won; this one must stand down (:class:`TakeoverRejected`), which
is the split-brain guard: authority is totalized by epoch, and the old
primary's still-arriving requests are dropped and counted replica-side.
The ``route.takeover`` fault point fires inside each per-replica
handshake so ``--fault_spec`` episodes drill partial adoptions
deterministically (docs/ROBUSTNESS.md).

The result of :meth:`Standby.adopt` is a fully seeded
:class:`~transformer_tpu.serve.router.Router` (epoch E+1, ``ha`` mode —
it immediately starts emitting its own beacon for the NEXT standby):
delivered orders excluded, recovered answers pre-seeded, replica-claimed
orders re-owned exactly once in the in-flight table, unknowns queued for
dispatch. Clients see at-most-once answers across the cutover: the
delivered-prefix floor, the replicas' epoch guard, and the adopting
funnel's duplicate drop together make the exactly-once drill in
tests/test_router.py hold under every interleaving the schedule checker
explores.

Threading: the standby is single-threaded until adoption (tail + poll);
after :meth:`adopt` the usual router contract applies (reader threads
feed the inbox, one pump thread owns the tables).
"""

from __future__ import annotations

import json
import socket
import time

from transformer_tpu.obs.trace import SpanContext
from transformer_tpu.serve.resilience import maybe_fail
from transformer_tpu.serve.router import ReplicaLink, Router, _Tracked


class TakeoverRejected(RuntimeError):
    """A replica answered the handshake with a HIGHER authority epoch:
    another standby already adopted the fleet. This standby must stand
    down — proceeding would be exactly the split brain the epoch
    totalizes away."""


class TakeoverLink(ReplicaLink):
    """A replica link over the worker's ``--ha`` control socket — the
    adopting router's transport. Same three-method surface as every other
    link; ``alive()`` is socket health (the worker process outlives its
    primary by design, so pipe liveness is the only observable)."""

    def __init__(self, index: int, name: str, sock, rfile, wfile,
                 role: str = "both"):
        super().__init__(index, name, role=role)
        self._sock = sock
        self._rf = rfile
        self._wf = wfile
        self._broken = False

    def send(self, msg: dict) -> None:
        if self._broken:
            raise BrokenPipeError(f"replica {self.name} control socket gone")
        try:
            self._wf.write(json.dumps(msg) + "\n")
            self._wf.flush()
        except (OSError, ValueError) as e:
            self._broken = True
            raise BrokenPipeError(str(e)) from e

    def alive(self) -> bool:
        return not self._broken

    def start_reader(self, inbox) -> None:
        import threading

        def _read():
            try:
                for line in self._rf:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(msg, dict):
                        inbox.put((self.index, msg))
            except (OSError, ValueError):
                pass
            self._broken = True
            inbox.put((self.index, {"type": "exit"}))

        threading.Thread(
            target=_read, name=f"standby-read-{self.name}", daemon=True
        ).start()

    def kill(self) -> None:
        self._broken = True
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self, timeout: float = 10.0) -> None:
        try:
            self.send({"type": "shutdown"})
        except (OSError, ValueError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class Standby:
    """Tail the primary's event log; adopt its fleet when it goes silent.

    ``router_kwargs`` is forwarded to the adopted :class:`Router`
    (telemetry, supervisor, scaler, slos, dispatch knobs) — the standby
    becomes a first-class primary, supervision tier included. ``clock``
    and the log reader are injectable so tests drive the death detector
    deterministically.
    """

    def __init__(
        self,
        log_path: str,
        *,
        takeover_after_s: float = 2.0,
        connect_timeout_s: float = 5.0,
        encode=None,
        bos_id: int = 1,
        telemetry=None,
        clock=time.monotonic,
        router_kwargs: "dict | None" = None,
    ):
        self.log_path = log_path
        self.takeover_after_s = takeover_after_s
        self.connect_timeout_s = connect_timeout_s
        self.encode = encode
        self.bos_id = bos_id
        self._tel = telemetry
        self._clock = clock
        self._router_kwargs = dict(router_kwargs or {})
        self._offset = 0
        self._partial = ""
        # Reconstructed primary state (all from the log tail).
        self.epoch = 1
        self.ports: "dict[str, int]" = {}
        self.intake: "dict[int, dict]" = {}
        self.max_order = -1          # highest order ever seen (intake is
        #                              pruned at delivery; the order clock
        #                              must still resume past everything)
        self.delivered_upto = 0      # _emit_next floor: client saw [0, upto)
        self._last_hb_local: "float | None" = None
        self._saw_hb = False
        self.stats = {
            "hb_seen": 0, "intake_seen": 0, "recovered_answers": 0,
            "reowned_inflight": 0, "redispatched": 0, "skipped_replicas": 0,
        }
        self._m_state = None
        if telemetry is not None:
            self._m_state = telemetry.registry.gauge(
                "route_standby_state",
                "0 = tailing the primary, 1 = adopting, 2 = primary",
            )
            self._m_state.set(0)

    # -- the tail (standby thread) -------------------------------------------

    def _read_new_events(self) -> "list[dict]":
        out: list[dict] = []
        try:
            with open(self.log_path) as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return out
        if not chunk:
            return out
        data = self._partial + chunk
        lines = data.split("\n")
        # The last element is either "" (chunk ended on a newline) or a
        # torn line mid-write — keep it for the next read either way.
        self._partial = lines.pop()
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict):
                out.append(ev)
        return out

    def _ingest(self, ev: dict) -> None:
        kind = ev.get("kind")
        if kind == "route.hb":
            self.stats["hb_seen"] += 1
            self._saw_hb = True
            self._last_hb_local = self._clock()
            self.epoch = max(self.epoch, int(ev.get("epoch", 1)))
            ports = ev.get("ports")
            if isinstance(ports, dict):
                self.ports = {
                    str(k): int(v)
                    for k, v in ports.items()
                    if isinstance(v, int)
                }
        elif kind == "route.intake":
            order = ev.get("order")
            if isinstance(order, int):
                self.stats["intake_seen"] += 1
                self.max_order = max(self.max_order, order)
                if order >= self.delivered_upto:
                    self.intake[order] = ev
        elif kind == "route.answered":
            upto = ev.get("upto")
            if isinstance(upto, int):
                self.delivered_upto = max(self.delivered_upto, upto + 1)
                # Delivered orders can never be re-owned or re-answered:
                # drop their intake records so a standby tailing a
                # long-running primary stays bounded by the IN-FLIGHT
                # window, not by every request ever accepted.
                for order in [
                    o for o in self.intake if o < self.delivered_upto
                ]:
                    del self.intake[order]

    def poll(self) -> float:
        """Ingest new log lines; returns seconds of heartbeat silence
        (0.0 until the first poll establishes a baseline)."""
        for ev in self._read_new_events():
            self._ingest(ev)
        now = self._clock()
        if self._last_hb_local is None:
            # Start the silence clock at first observation: a standby
            # pointed at a log whose primary is ALREADY dead must still
            # time out (there will never be a fresh heartbeat to see).
            self._last_hb_local = now
            return 0.0
        return now - self._last_hb_local

    @property
    def primary_dead(self) -> bool:
        return (
            self._last_hb_local is not None
            and self._clock() - self._last_hb_local > self.takeover_after_s
        )

    def run_until_takeover(
        self, poll_s: float = 0.1, timeout: "float | None" = None,
        sleep=time.sleep,
    ) -> Router:
        """The standby main loop: tail until the primary goes silent,
        then :meth:`adopt`. ``timeout`` bounds the wait (None = forever)."""
        t0 = self._clock()
        self.poll()
        while not self.primary_dead:
            if timeout is not None and self._clock() - t0 > timeout:
                raise TimeoutError(
                    f"primary still alive after {timeout}s of standby"
                )
            sleep(poll_s)
            self.poll()
        return self.adopt()

    # -- the takeover (once) -------------------------------------------------

    def _handshake(
        self, index: int, name: str, port: int, ask: "list[int]"
    ) -> "tuple[TakeoverLink, dict, dict]":
        maybe_fail("route.takeover")
        sock = socket.create_connection(
            ("127.0.0.1", port), timeout=self.connect_timeout_s
        )
        wf = sock.makefile("w", encoding="utf-8", buffering=1)
        rf = sock.makefile("r", encoding="utf-8")
        wf.write(json.dumps({
            "type": "takeover", "epoch": self.epoch + 1, "inflight": ask,
        }) + "\n")
        wf.flush()
        line = rf.readline()
        if not line:
            raise OSError(f"replica {name} closed the control socket")
        reply = json.loads(line)
        if reply.get("type") == "rejected":
            sock.close()
            raise TakeoverRejected(
                f"replica {name} is owned by epoch {reply.get('epoch')} "
                f">= {self.epoch + 1}: another standby adopted the fleet"
            )
        if reply.get("type") != "adopted":
            raise OSError(f"replica {name} answered {reply.get('type')!r}")
        sock.settimeout(None)
        link = TakeoverLink(
            index, name, sock, rf, wf, role=str(reply.get("role", "both")),
        )
        # The adopting router's own HA beacon must advertise the control
        # ports (the workers only announce them once, at bootstrap) — a
        # SECOND standby adopts from the new primary's journal the same
        # way the first did from the original's.
        link.control_port = port
        statuses = reply.get("statuses") or {}
        messages = reply.get("messages") or {}
        return link, statuses, messages

    def _rebuild_tracked(self, order: int, now: float) -> _Tracked:
        ev = self.intake.get(order) or {}
        req = ev.get("req")
        if not isinstance(req, dict):
            req = {"prompt": ""}
        ctx = SpanContext.from_traceparent(ev.get("traceparent"))
        if ctx is None:
            ctx = SpanContext.new()
        deadline = None
        d = ev.get("deadline_ms")
        ts = ev.get("ts")
        if isinstance(d, (int, float)) and isinstance(ts, (int, float)):
            # Remaining budget measured against wall time elapsed since
            # the intake record was written: the deadline contract rides
            # the cutover (an order whose budget died with the primary
            # answers a structured deadline error, not a zombie success).
            remaining = (ts + d / 1e3) - time.time()
            deadline = now + remaining
        return _Tracked(
            order=order, req=req, ctx=ctx, t_submit=now, deadline=deadline,
            affinity=None,
        )

    def adopt(self) -> Router:
        """Perform the takeover: handshake every known replica, re-own the
        inflight table exactly once, and return the seeded router (epoch
        bumped, ``ha`` mode on — the next standby tails US)."""
        if self._m_state is not None:
            self._m_state.set(1)
        now = time.perf_counter()
        undelivered = sorted(
            o for o in self.intake if o >= self.delivered_upto
        )
        done: "dict[int, dict]" = {}
        ask: "list[int]" = []
        for order in undelivered:
            resp = self.intake[order].get("resp")
            if isinstance(resp, dict):
                done[order] = resp  # pre-answered at the primary (parse
                #                     errors): the log alone recovers it
            else:
                ask.append(order)
        links: "list[TakeoverLink]" = []
        statuses: "dict[int, tuple[str, int]]" = {}
        messages: "dict[int, dict]" = {}
        failed: "list[str]" = []
        for name in sorted(self.ports):
            index = len(links)
            try:
                link, sts, msgs = self._handshake(
                    index, name, self.ports[name], ask
                )
            except TakeoverRejected:
                raise
            except (OSError, ValueError):
                # route.takeover fault / dead worker / torn reply: a
                # partial adoption — the missing replica's claimed work
                # surfaces as "unknown" elsewhere and re-dispatches.
                self.stats["skipped_replicas"] += 1
                failed.append(name)
                continue
            links.append(link)
            for rid_s, status in sts.items():
                try:
                    rid = int(rid_s)
                except (TypeError, ValueError):
                    continue
                # Strongest claim wins: "done" (the answer is already
                # computed — replaying beats re-owning) over "inflight"
                # (the owner keeps it) over "unknown" (every replica
                # reports every asked rid, so an early peer's "unknown"
                # must never block the real owner's later claim).
                rank = {"done": 2, "inflight": 1}.get(status, 0)
                cur = statuses.get(rid)
                if cur is None or rank > {"done": 2, "inflight": 1}.get(
                    cur[0], 0
                ):
                    statuses[rid] = (status, index)
                if status == "done":
                    msg = msgs.get(rid_s)
                    if isinstance(msg, dict):
                        messages[rid] = msg
        if not links:
            if self._m_state is not None:
                self._m_state.set(0)
            raise RuntimeError(
                "takeover adopted zero replicas "
                f"(ports={self.ports}, failed={failed})"
            )
        inflight: "list[tuple[int, _Tracked]]" = []
        pending: "list[_Tracked]" = []
        for order in ask:
            status, index = statuses.get(order, ("unknown", -1))
            msg = messages.get(order)
            if status == "done" and isinstance(msg, dict) and isinstance(
                msg.get("resp"), dict
            ):
                # Recovered: the answer died in the primary's pipe but
                # lives in the replica's re-delivery cache.
                done[order] = msg["resp"]
                self.stats["recovered_answers"] += 1
            elif status == "inflight":
                rr = self._rebuild_tracked(order, now)
                inflight.append((index, rr))
                self.stats["reowned_inflight"] += 1
            else:
                # unknown everywhere (or a non-answer replay, e.g. a
                # disaggregation handoff that died with the primary):
                # re-dispatch from the intake record.
                pending.append(self._rebuild_tracked(order, now))
                self.stats["redispatched"] += 1
        router = Router(
            links,
            encode=self.encode,
            bos_id=self.bos_id,
            telemetry=self._tel,
            ha=True,
            epoch=self.epoch + 1,
            **self._router_kwargs,
        )
        router.seed_takeover(
            next_order=max(self.max_order + 1, self.delivered_upto),
            emit_next=self.delivered_upto,
            done=done,
            inflight=inflight,
            pending=pending,
        )
        for link in links:
            link.start_reader(router.inbox)
        if self._m_state is not None:
            self._m_state.set(2)
        if self._tel is not None:
            self._tel.emit(
                "route.takeover",
                epoch=self.epoch + 1,
                adopted=[l.name for l in links],
                failed=failed,
                recovered_answers=self.stats["recovered_answers"],
                reowned_inflight=self.stats["reowned_inflight"],
                redispatched=self.stats["redispatched"],
                delivered_upto=self.delivered_upto,
            )
        return router
