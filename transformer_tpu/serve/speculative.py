"""Speculative decoding for the LM serving path: draft, verify, rollback.

Incremental decoding is memory-bandwidth-bound — every step moves all
params plus the whole KV cache to emit ONE token ("Fast Transformer
Decoding", Shazeer, arXiv:1911.02150) — so past chunked prefill the next
serving win is more tokens per model forward. Speculative decoding gets
there without touching the model: a cheap DRAFTER proposes ``k`` candidate
continuation tokens, one matmul-rich verify forward
(``models.transformer.transformer_verify`` — the same S_q > 1 cache-write
path chunked prefill rides) scores all ``k + 1`` positions at once, and the
longest draft prefix the model agrees with is accepted. Rollback of the
rejected tail is O(1): reset ``cache["index"]``
(``ops.attention.rollback_cache``) — stale K/V beyond the index are already
invisible to the offset causal mask, and the next real write overwrites
them in place.

Two drafters ship behind one duck-typed interface
(``start(prompt_ids) -> state``; ``propose(state, context, k) -> tokens``):

- :class:`NgramDrafter` — model-free prompt-lookup (Saxena-style): propose
  the continuation of the most recent earlier occurrence of the context's
  suffix n-gram. Zero extra params or forwards; strong on translation,
  summarization-with-quotes, and code, where output copies input spans.
- :class:`ModelDrafter` — a small draft model sharing the target
  tokenizer: greedy proposals from its own KV cache, synced to the
  accepted history by the same rollback-by-index trick.

Acceptance is LOSSLESS. Greedy requests accept draft ``d_{j+1}`` iff it
equals ``argmax`` of the verify logits at position ``j`` — the emitted
stream is byte-identical to plain greedy decode (pinned by
``tests/test_speculative.py``). Sampled requests use standard
rejection-sampling acceptance (Leviathan et al., arXiv:2211.17192): accept
``d`` with probability ``p(d)`` (the drafters are deterministic, so the
draft distribution is a point mass), else emit a draw from the residual
``p`` with ``d`` removed — the OUTPUT DISTRIBUTION equals plain sampling,
though individual draws differ (different rng consumption).

Rolling-window caches (``attention_window``) are structurally incompatible
with rollback-by-index — a speculative write evicts a slot that may still
be in-window after rollback — so speculation is refused for those configs
(``rollback_cache`` raises; the scheduler gates at construction).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Protocol, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.seeding import keyed_rng
from transformer_tpu.models.decoder import init_decoder_caches
from transformer_tpu.models.transformer import (
    transformer_prefill,
    transformer_verify,
)
from transformer_tpu.ops.attention import rollback_cache
from transformer_tpu.serve.resilience import maybe_fail
from transformer_tpu.train.decode import _bucket, prefill_len_for, sample_token


def _drafter_fault_points() -> None:
    """The two drafter chaos points (docs/ROBUSTNESS.md): ``draft.propose``
    (a failing drafter — raises; the scheduler's speculative breaker
    fails speculation open to the plain byte-parity path) and
    ``draft.slow`` (a stalling drafter — sleeps ``ms=``; trips the
    scheduler's slow-drafter budget and request deadlines). No-ops without
    an installed plane."""
    maybe_fail("draft.propose")
    maybe_fail("draft.slow")


class Drafter(Protocol):
    """What the scheduler and the standalone loop require of a drafter."""

    def start(self, prompt_ids: Sequence[int]) -> Any:
        """Per-request draft state (None for stateless drafters)."""

    def propose(self, state: Any, context: Sequence[int], k: int) -> list[int]:
        """Up to ``k`` candidate tokens continuing ``context`` (the full
        determined token history: prompt + accepted generations). May
        return fewer than ``k`` (or none) when it has nothing credible —
        the verify row simply carries fewer candidates that round."""


# --------------------------------------------------------------------------
# drafters


@dataclasses.dataclass
class _NgramState:
    """Incremental per-request lookup index: n-gram tuple -> start
    positions (ascending). Contexts only ever GROW (the verified history is
    append-only), so each ``propose`` indexes just the new tail — O(max_n)
    per new token instead of rescanning the whole context every step."""

    ctx: list[int] = dataclasses.field(default_factory=list)
    occ: dict[tuple[int, ...], list[int]] = dataclasses.field(
        default_factory=dict
    )


class NgramDrafter:
    """Model-free prompt-lookup drafting: find the most recent earlier
    occurrence of the context's trailing n-gram and propose the tokens that
    followed it. Tries the longest suffix first (``max_n`` down to
    ``min_n``) so a long exact match wins over a short ambiguous one."""

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got {min_n}/{max_n}")
        self.max_n = max_n
        self.min_n = min_n

    def start(self, prompt_ids: Sequence[int]) -> _NgramState:
        return _NgramState()

    def _index(self, state: _NgramState, context: Sequence[int]) -> list[int]:
        ctx, occ = state.ctx, state.occ
        new = [int(t) for t in context[len(ctx):]]
        # Contexts are append-only by construction (the verified history
        # never rewinds); spot-check the boundary token instead of
        # re-comparing the whole prefix — a full compare would make every
        # propose O(context) and defeat the incremental index.
        assert not ctx or len(context) < len(ctx) or (
            int(context[len(ctx) - 1]) == ctx[-1]
        ), "NgramDrafter contexts must grow append-only"
        for tok in new:
            ctx.append(tok)
            for n in range(self.min_n, self.max_n + 1):
                if len(ctx) >= n:
                    occ.setdefault(tuple(ctx[-n:]), []).append(len(ctx) - n)
        return ctx

    def propose(
        self, state: _NgramState | None, context: Sequence[int], k: int
    ) -> list[int]:
        _drafter_fault_points()
        if state is None:  # stateless callers pay the one-shot index cost
            state = _NgramState()
        ctx = self._index(state, context)
        for n in range(min(self.max_n, len(ctx) - 1), self.min_n - 1, -1):
            # Most recent earlier occurrence WITH a full k-token
            # continuation wins; a match hugging the context's end (the
            # common case in cyclic text — the previous period of the
            # cycle) has almost no tokens after it, so it is only the
            # fallback. Overlap with the suffix itself is fine.
            starts = state.occ.get(tuple(ctx[-n:]), [])
            fallback: list[int] | None = None
            for start in reversed(starts):
                if start == len(ctx) - n:
                    continue  # the suffix itself
                cont = ctx[start + n : start + n + k]
                if len(cont) == k:
                    return cont
                if cont and fallback is None:
                    fallback = cont
            if fallback:
                return fallback
        return []


@partial(jax.jit, static_argnames=("cfg",))
def _draft_ingest(params, caches, toks, cfg: ModelConfig):
    """Feed (1, w) tokens into the draft model's cache at its own index;
    returns ((1, V) last-position logits, caches). Widths are powers of two
    (the sync loop below splits deltas that way), so the compile set stays
    O(log max_len)."""
    return transformer_prefill(
        params, toks, None, None, caches, caches[0]["index"], cfg
    )


@dataclasses.dataclass
class _DraftState:
    caches: list[dict[str, Any]]
    fed: list[int]


class ModelDrafter:
    """A small decoder-only draft model sharing the target tokenizer.

    Keeps one batch-1 KV cache per request, greedy-extends from it, and
    re-syncs to the verified history by the same O(1) rollback-by-index
    mechanism the target model uses: roll back to the longest common prefix
    of what it fed and what was actually accepted, then re-ingest the delta
    in power-of-two chunks."""

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        max_total: int,
        eos_id: int | None = None,
        target_vocab_size: int | None = None,
    ):
        if not cfg.decoder_only:
            raise ValueError("ModelDrafter needs a decoder-only draft model")
        if cfg.attention_window:
            raise ValueError(
                "ModelDrafter cannot use a rolling-window cache: rollback "
                "by index cannot restore evicted slots"
            )
        if (
            target_vocab_size is not None
            and cfg.target_vocab_size != target_vocab_size
        ):
            # Fail at construction, not mid-serve: a draft token id outside
            # the target vocab would index past the target's (V,) logits in
            # the acceptance path and kill every in-flight request.
            raise ValueError(
                f"draft model vocab ({cfg.target_vocab_size}) != target "
                f"vocab ({target_vocab_size}) — speculative drafting "
                "requires a SHARED tokenizer"
            )
        self.params, self.cfg = params, cfg
        self.max_total = max_total
        self.eos_id = eos_id

    def start(self, prompt_ids: Sequence[int]) -> _DraftState:
        return _DraftState(
            caches=init_decoder_caches(self.cfg, 1, self.max_total), fed=[]
        )

    def propose(
        self, state: _DraftState, context: Sequence[int], k: int
    ) -> list[int]:
        _drafter_fault_points()
        ctx = [int(t) for t in context]
        # The draft model's own position/buffer budget caps how far ahead
        # it can look; a capped (or empty) proposal list is always valid.
        k = min(k, self.max_total - 1 - len(ctx),
                self.cfg.max_position - len(ctx))
        if k <= 0 or not ctx:
            return []
        # Re-sync: keep the longest common prefix of (fed, ctx), capped one
        # short of ctx so the final context token is always re-fed — its
        # forward produces the logits the first proposal comes from.
        m = 0
        limit = min(len(state.fed), len(ctx) - 1)
        while m < limit and state.fed[m] == ctx[m]:
            m += 1
        if m < len(state.fed):
            state.caches = [rollback_cache(c, m) for c in state.caches]
            state.fed = state.fed[:m]
        delta = ctx[m:]
        logits = None
        while delta:
            w = prefill_len_for(len(delta)) or 1
            logits, state.caches = _draft_ingest(
                self.params, state.caches,
                jnp.asarray([delta[:w]], jnp.int32), self.cfg,
            )
            state.fed.extend(delta[:w])
            delta = delta[w:]
        out: list[int] = []
        for i in range(k):
            d = int(np.argmax(np.asarray(logits[0])))
            out.append(d)
            if self.eos_id is not None and d == self.eos_id:
                break  # nothing credible follows EOS
            if i + 1 < k:
                logits, state.caches = _draft_ingest(
                    self.params, state.caches,
                    jnp.asarray([[d]], jnp.int32), self.cfg,
                )
                state.fed.append(d)
        return out


def drafter_from_flags(
    draft_checkpoint: str,
    draft_ngram: int,
    max_total: int,
    eos_id: int | None = None,
    target_vocab_size: int | None = None,
):
    """Build the configured drafter: a draft-model export when
    ``draft_checkpoint`` names one (loaded via the same ``load_export``
    path the serving CLIs use — it must share the target tokenizer, which
    ``target_vocab_size`` enforces at startup), else the model-free n-gram
    drafter with ``draft_ngram`` as its longest lookup n-gram."""
    if draft_checkpoint:
        from transformer_tpu.cli.translate import load_export

        d_params, d_cfg = load_export(draft_checkpoint)
        return ModelDrafter(
            d_params, d_cfg, max_total, eos_id=eos_id,
            target_vocab_size=target_vocab_size,
        )
    return NgramDrafter(max_n=max(1, draft_ngram))


# --------------------------------------------------------------------------
# verify-row planning and judging (shared by the scheduler and the
# standalone loop — ONE acceptance rule, so the two paths cannot drift)


def build_verify_row(
    history: Sequence[int],
    pos: int,
    k: int,
    drafter: Drafter | None,
    dstate: Any,
) -> tuple[list[int], int]:
    """Plan one verify forward for a stream whose cache holds positions
    ``< pos``: ``row[0]`` is the pending token ``history[pos]``, followed by
    up to ``k`` lookahead tokens — already-determined history first (the
    un-ingested prompt tail, teacher-forced exactly like chunked prefill),
    then drafter proposals continuing the END of the history. Returns
    ``(row, n_drafted)``; ``len(row) <= k + 1``."""
    history = list(history)
    row = [int(history[pos])]
    forced = [int(t) for t in history[pos + 1 : pos + 1 + k]]
    row.extend(forced)
    n_drafted = 0
    want = k - len(forced)
    if want > 0 and drafter is not None:
        props = [int(t) for t in drafter.propose(dstate, history, want)]
        props = props[:want]
        row.extend(props)
        n_drafted = len(props)
    return row, n_drafted


def judge_row(
    row: Sequence[int],
    pos: int,
    prompt_len: int,
    accept: Callable[[int, int], tuple[bool, int]],
    bonus: Callable[[int], int],
) -> tuple[list[int], int, int]:
    """Walk one verify row's picks, applying the acceptance rule.

    ``accept(j, draft) -> (accepted, token)`` judges the draft fed at row
    index ``j + 1`` against position ``j``'s verify output (greedy: token
    is the argmax pick, accepted iff it equals the draft; sampling: the
    rejection-sampling draw). ``bonus(j)`` picks the free extra token when
    every draft before the row's end survived. Positions still inside the
    prompt are teacher-forced — their picks are discarded, exactly like
    the in-prompt ticks of ``lm_generate``.

    Returns ``(emitted, keep, n_accepted)``: the generated tokens in order,
    how many fed tokens remain VALID in the cache (the caller rolls the
    index back to ``pos + keep``), and how many drafts were accepted. The
    last emitted token (mismatch draw or bonus) has NOT been ingested — it
    is the stream's next pending token."""
    emitted: list[int] = []
    n_accepted = 0
    for j in range(len(row)):
        if pos + j + 1 < prompt_len:
            continue  # next position is still prompt: pick discarded
        if j + 1 < len(row):
            ok, tok = accept(j, int(row[j + 1]))
            emitted.append(int(tok))
            if not ok:
                return emitted, j + 1, n_accepted
            n_accepted += 1
        else:
            emitted.append(int(bonus(j)))
            return emitted, j + 1, n_accepted
    return emitted, len(row), n_accepted


def filtered_probs(
    logits: np.ndarray, temperature: float, top_k: int, top_p: float
) -> np.ndarray:
    """The ``sample_token`` distribution (f32 softmax over temperature-
    scaled logits, optional top-k then top-p truncation) replicated in
    numpy — rejection-sampling acceptance needs the probability the target
    model assigns to a draft token, which never leaves the device on the
    plain sampling path."""
    logits = np.asarray(logits, np.float32) / max(float(temperature), 1e-6)
    if top_k > 0:
        kth = np.sort(logits)[-min(top_k, logits.size)]
        logits = np.where(logits < kth, -np.inf, logits)
    if top_p < 1.0:
        order = np.sort(logits)[::-1]
        shifted = order - order[0]
        probs = np.exp(shifted) / np.sum(np.exp(shifted))
        exclusive = np.cumsum(probs) - probs
        kept = exclusive < top_p
        thresh = np.min(np.where(kept, order, np.inf))
        logits = np.where(logits < thresh, -np.inf, logits)
    logits = logits - np.max(logits)
    p = np.exp(logits)
    return p / np.sum(p)


def sampled_accept(
    probs: np.ndarray, draft: int, rng: np.random.Generator
) -> tuple[bool, int]:
    """Standard rejection-sampling acceptance against a deterministic
    drafter (draft distribution = point mass): accept ``draft`` with
    probability ``p(draft)``; on rejection draw from the residual ``p``
    with the draft's mass removed. Output distribution == plain sampling."""
    p_d = float(probs[draft])
    if rng.random() < p_d:
        return True, draft
    resid = probs.copy()
    resid[draft] = 0.0
    total = float(resid.sum())
    if total <= 0.0:
        # The draft carried ALL the mass — acceptance probability was 1,
        # so this branch is unreachable except for fp dust; emit the draft.
        return True, draft
    return False, int(rng.choice(len(resid), p=resid / total))


# --------------------------------------------------------------------------
# standalone speculative generation (batch-1 host loop)


@partial(jax.jit, static_argnames=("cfg",))
def _verify(params, caches, toks, cfg: ModelConfig):
    """One verify forward for a (1, w) row at the cache's own index."""
    pos = caches[0]["index"]
    return transformer_verify(params, toks, caches, pos, cfg)


def verify_row_picks(
    logits, base_key, position, temperature, *, sample, top_k, top_p
):
    """(w, V) verify logits -> (w,) picks, one per fed position, with the
    same position-keyed rng folding ``lm_generate`` uses (``fold_in(rng,
    position + j)``) so sampled draws are deterministic per position. THE
    one definition of the verify-pick math — the standalone loop jits it
    directly (``_pick_row``) and the scheduler vmaps it over the slot pool
    (``_pick_pool_verify``), so the two paths cannot drift."""

    def one(row_logits, j):
        key = jax.random.fold_in(base_key, position + j)
        return sample_token(
            row_logits[None], key, sample=sample, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )[0]

    return jax.vmap(one)(logits, jnp.arange(logits.shape[0]))


_pick_row = partial(jax.jit, static_argnames=("sample", "top_k", "top_p"))(
    verify_row_picks
)


def speculative_generate(
    params,
    cfg: ModelConfig,
    prompt_ids: Sequence[int],
    max_new: int,
    eos_id: int,
    *,
    speculate_k: int,
    drafter: Drafter | None = None,
    sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
    prefill_chunk: int = 0,
) -> tuple[list[int], dict]:
    """Batch-1 speculative continuation of a BOS-led prompt.

    Returns ``(tokens, stats)`` where ``tokens`` is the generated stream
    (EOS included when generated, like an ``lm_generate`` row before its
    PAD tail) and ``stats`` counts ``verify_forwards`` / ``drafted`` /
    ``accepted`` — tokens-per-forward is ``len(tokens) /
    verify_forwards``. Greedy output is byte-identical to
    ``lm_generate``'s (test-pinned); sampled output is
    distribution-lossless via rejection sampling.
    """
    if cfg.attention_window:
        raise ValueError(
            "speculative decoding cannot roll back a rolling-window cache "
            "(attention_window configs serve non-speculatively)"
        )
    if speculate_k < 1:
        raise ValueError(f"speculate_k must be >= 1, got {speculate_k}")
    ids = [int(t) for t in prompt_ids]
    L = len(ids)
    if L < 1:
        raise ValueError("prompt must carry at least the BOS token")
    max_new = min(max_new, cfg.max_position - L)
    if drafter is None:
        drafter = NgramDrafter()
    # Power-of-two cache buffer (speculate_k slack keeps boundary-straddling
    # verify writes in-bounds): buffer size is a compiled shape, so bucketing
    # keeps the verify/pick compile set O(log max_len) across prompt lengths
    # — same reason generate() buckets its prompt widths. Oversized rows are
    # invisible (the prefix mask hides everything >= index).
    buf = _bucket(
        L + max_new + 1 + speculate_k,
        cfg.max_position + 1 + speculate_k, floor=8,
    )
    caches = init_decoder_caches(cfg, 1, buf)
    base_key = jax.random.PRNGKey(seed)
    stats = {"verify_forwards": 0, "drafted": 0, "accepted": 0}
    if max_new < 1:
        return [], stats

    # Bucketed prefill of the prompt prefix (one short of the full prompt,
    # so the boundary pick is always made by a verify forward).
    history = list(ids)
    pos = 0
    n = min(prefill_len_for(L, prefill_chunk), L - 1)
    if n >= 1:
        _, caches = transformer_prefill(
            params, jnp.asarray([ids[:n]], jnp.int32), None, None, caches,
            0, cfg, chunk=prefill_chunk,
        )
        pos = n
    dstate = drafter.start(ids)
    out: list[int] = []
    finished = False
    while not finished:
        # Cap the row so its writes stay inside the cache buffer.
        k_row = min(speculate_k, buf - pos - 1)
        row, n_drafted = build_verify_row(history, pos, k_row, drafter, dstate)
        stats["drafted"] += n_drafted
        toks = jnp.asarray([row], jnp.int32)
        logits, caches = _verify(params, caches, toks, cfg)
        stats["verify_forwards"] += 1
        picks = np.asarray(
            _pick_row(
                logits[0], base_key, jnp.int32(pos),
                jnp.float32(temperature),
                sample=sample, top_k=top_k, top_p=top_p,
            )
        )
        if sample:
            logits_np = np.asarray(logits[0], np.float32)

            def accept(j, draft):
                probs = filtered_probs(
                    logits_np[j], temperature, top_k, top_p
                )
                return sampled_accept(probs, draft, keyed_rng(seed, pos + j))

        else:

            def accept(j, draft):
                pick = int(picks[j])
                return pick == draft, pick

        emitted, keep, n_accepted = judge_row(
            row, pos, L, accept, lambda j: int(picks[j])
        )
        n_consumed = 0
        for tok in emitted:
            if len(out) >= max_new:
                finished = True
                break
            n_consumed += 1
            out.append(int(tok))
            if tok == eos_id:
                finished = True
                break
        # Only consumed emissions count toward acceptance telemetry (the
        # row's post-EOS/post-budget tail was judged but never emitted).
        stats["accepted"] += min(n_accepted, n_consumed)
        if finished:
            break
        pos += keep
        history = ids + out
        # O(1) rollback: hide the rejected tail from every later read.
        caches = [rollback_cache(c, pos) for c in caches]
    return out, stats
