"""Benchmark: Transformer-base training throughput, tokens/sec/chip.

Runs the flagship train step (BASELINE.json configs[1]: 6L, d_model=512,
8 heads, dff=2048, bf16 compute) on whatever accelerator jax exposes (the
driver runs this on one real TPU chip), times steady-state steps, and prints
ONE JSON line:

    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": X}

``vs_baseline`` is null: the reference publishes no numbers (BASELINE.md —
README is a bare feature list), so there is nothing to normalize against.

Resilience: the TPU tunnel can be transiently down (round 1 captured exactly
that: ``jax.errors.JaxRuntimeError: UNAVAILABLE`` at backend init). A failed
backend init is cached for the life of the process, so the measurement runs
in a child process; the parent retries with bounded backoff and, if every
attempt fails, emits a structured failure JSON line instead of a traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_INNER_ENV = "_TRANSFORMER_TPU_BENCH_INNER"
_METRIC = "transformer-base train throughput (6L/512/8H/2048, bf16, batch 64, seq 64)"
# Banked-measurement stores. bench.py appends its own successful base rows to
# bench_rows.jsonl and, on a relay outage, falls back to the newest banked
# TPU base row (marked stale) instead of emitting value:null — a relay that
# is down during the driver's bench window must not erase a number measured
# an hour earlier in the same round. The watchdog's repeat-base rows land in
# bench_extras.jsonl (watch_and_run.sh $EXTRA), so the fallback scans both.
_REPO_DIR = os.path.dirname(os.path.abspath(__file__))
_ROWS_FILE = os.path.join(_REPO_DIR, "bench_rows.jsonl")
_BANK_FILES = (_ROWS_FILE, os.path.join(_REPO_DIR, "bench_extras.jsonl"))
_BANK_METRIC = "base train throughput"
# HARD total wall-clock budget for the whole script (attempts + sleeps +
# child timeouts). Round 2's retry ladder could run ~54 minutes and the
# driver killed the process (rc=124) before the structured failure line was
# printed (BENCH_r02.json: parsed=null). The budget guarantees the one JSON
# line is always emitted well inside any plausible driver timeout.
#
# Tradeoff, chosen deliberately: a healthy first attempt gets ~200 s, which
# covers the measured profile (~20-40 s cold XLA compile + ~1 s of timing
# loop, r2: base measured at rc=0 well inside this, plus one optional
# second compile for the multistep field below) but would fail a
# pathologically slow backend. That failure is still a PARSEABLE line —
# recoverable by the judge — whereas exceeding the driver's window repeats
# the unrecoverable rc=124/parsed=null. Short-and-parseable beats
# long-and-killed.
_TOTAL_BUDGET_S = 220.0
# A relay port that answers "connection refused" in <1s is DOWN, not slow —
# round 4 burned the whole budget re-probing it in 10s sleeps (19 cycles)
# before the banked fallback row finally went out at the rc=124 edge. Three
# quick probes catch a relay mid-restart; after that the stale banked row is
# emitted immediately, leaving the driver's window untouched.
_RELAY_MAX_PROBES = 3
# Bench-infra attribution log (docs/OBSERVABILITY.md): relay-down probes,
# failed attempts, and fallback-row emissions land here as JSONL events so a
# round's flaky bench window is diagnosable afterwards with
# `python -m transformer_tpu.obs summarize bench_events.jsonl`.
_EVENTS_FILE = os.path.join(_REPO_DIR, "bench_events.jsonl")
_events = None


def _emit_event(kind: str, **fields) -> None:
    """Best-effort structured event (EventLog itself downgrades OSError to a
    one-time warning — attribution must never fail the benchmark)."""
    global _events
    if _events is False:
        return
    try:
        if _events is None:
            from transformer_tpu.obs import EventLog

            _events = EventLog(_EVENTS_FILE)
        _events.emit(kind, **fields)
    except (ImportError, OSError) as e:
        # ImportError: bench.py copied out of the repo. OSError: EventLog's
        # constructor itself (open/makedirs) on an unwritable repo dir —
        # emit() downgrades internally, but the constructor cannot.
        print(f"bench attribution disabled: {e!r}", file=sys.stderr)
        _events = False  # don't retry the constructor every event


def _run_inner() -> None:
    """The actual measurement. Runs in a child process (fresh backend)."""
    _t_start = time.monotonic()

    import jax
    import numpy as np

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.train import create_train_state, make_train_step
    from transformer_tpu.utils import enable_compilation_cache

    # The bench window is wall-clock-capped: a cache hit on the ~20-40 s
    # compile (or on a backend that cannot serialize, a no-op) directly
    # raises the odds the window fits.
    enable_compilation_cache()

    batch, seq = 64, 64
    model_cfg = ModelConfig(
        num_layers=6,
        d_model=512,
        num_heads=8,
        dff=2048,
        input_vocab_size=32002,
        target_vocab_size=32002,
        max_position=seq,
        dropout_rate=0.1,
        dtype="bfloat16",
    )
    train_cfg = TrainConfig(
        batch_size=batch, sequence_length=seq, warmup_steps=4000,
    )

    dev = jax.devices()[0]
    print(f"benchmarking on {dev.platform}:{dev.device_kind}", file=sys.stderr)

    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=(0,))
    rng = jax.random.PRNGKey(1)
    r = np.random.default_rng(0)
    src = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))
    tgt = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))

    # Warmup: compile + 2 steady steps. Synchronize via a VALUE fetch, not
    # block_until_ready: on tunneled/remote PJRT backends block_until_ready
    # can return before device execution finishes, inflating throughput.
    for _ in range(3):
        state, metrics = step(state, src, tgt, rng)
    float(metrics["loss"])

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, src, tgt, rng)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss"

    # Tokens processed per optimizer step: target tokens (the unit BLEU-side
    # throughput is quoted in). src+tgt would double-count the same sentence.
    tokens_per_step = batch * (seq - 1)
    value = tokens_per_step * n_steps / dt

    # Rough MFU estimate for context (stderr only): 6*P FLOPs/token fwd+bwd*3.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    flops_per_token = 6 * n_params
    print(
        f"{n_steps} steps in {dt:.2f}s, {value:,.0f} tok/s, "
        f"~{value * flops_per_token / 1e12:.2f} TFLOP/s model-flops "
        f"({n_params / 1e6:.1f}M params)",
        file=sys.stderr,
    )
    result = {
        "metric": _METRIC,
        "value": round(value, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,
        "device": f"{dev.platform}:{dev.device_kind}",
    }

    # Production dispatch path (TrainConfig.steps_per_dispatch): the same 20
    # optimizer steps inside ONE jitted scan with distinct stacked batches —
    # what --steps_per_dispatch buys a real run by amortizing per-step host
    # dispatch. Reported as an extra field (the headline stays the plain
    # per-step dispatch number); skipped, never fatal, if the budget is
    # tight or the second compile fails.
    try:
        if time.monotonic() - _t_start < 100.0:
            from transformer_tpu.train.trainer import make_multistep_train_step

            multi = jax.jit(
                make_multistep_train_step(make_train_step(model_cfg, train_cfg)),
                donate_argnums=(0,),
            )
            srcs = jax.device_put(
                r.integers(1, 32000, (n_steps, batch, seq), dtype=np.int32)
            )
            tgts = jax.device_put(
                r.integers(1, 32000, (n_steps, batch, seq), dtype=np.int32)
            )
            state, metrics = multi(state, srcs, tgts, rng)  # compile + warm
            float(metrics["loss"])
            t0 = time.perf_counter()
            state, metrics = multi(state, srcs, tgts, rng)
            float(metrics["loss"])
            ms_dt = time.perf_counter() - t0
            result["multistep_tokens_per_sec"] = round(
                tokens_per_step * n_steps / ms_dt, 1
            )
            result["multistep_note"] = (
                f"steps_per_dispatch={n_steps}: one dispatch, {n_steps} "
                "optimizer steps on distinct stacked batches"
            )
    except Exception as e:  # noqa: BLE001 — optional field only
        print(f"multistep field skipped: {e!r}", file=sys.stderr)

    print(json.dumps(result))


def _bank_success(stdout: str) -> None:
    """Append the fresh base measurement to the shared banked-rows file.

    Stored under the short watchdog-style metric name so the staleness
    fallback (and BASELINE.md bookkeeping) has one place to look. Banking is
    best-effort: a read-only disk must not turn a successful bench into rc=1.
    """
    try:
        row = json.loads(stdout.strip().splitlines()[-1])
        banked = {
            "metric": _BANK_METRIC,
            "value": row["value"],
            "unit": row["unit"],
            "vs_baseline": None,
            "device": row.get("device", ""),
            "source": "bench.py",
            "ts": round(time.time(), 1),
        }
        if "multistep_tokens_per_sec" in row:
            banked["multistep_tokens_per_sec"] = row["multistep_tokens_per_sec"]
        with open(_ROWS_FILE, "a") as f:
            f.write(json.dumps(banked) + "\n")
    except Exception as e:  # noqa: BLE001 — bookkeeping only
        print(f"banking skipped: {e!r}", file=sys.stderr)


def _latest_banked_base() -> tuple[dict, str] | None:
    """Newest banked base-config TPU row with a real value, plus its file.

    Rows without a ``device`` containing "tpu" are skipped: a CPU-fallback
    measurement must never be served as a stale tokens/sec/chip number.
    "Newest" is by the ``ts`` field bench.py stamps on its banked rows;
    rows without one (watchdog/seeded rows) rank as ts=0 and fall back to
    scan order, which is append order within each file.
    """
    best, best_path, best_ts = None, "", -1.0
    for path in _BANK_FILES:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                row = json.loads(line)
            except ValueError:
                continue
            try:
                usable = (
                    row.get("metric") == _BANK_METRIC
                    and row.get("value")
                    and "tpu" in row.get("device", "").lower()
                )
                ts = float(row.get("ts", 0.0))
            except (AttributeError, TypeError, ValueError):
                continue  # one malformed row must not break the contract
            if usable and ts >= best_ts:
                best, best_path, best_ts = row, path, ts
    if best is None:
        return None
    return best, best_path


def _looks_retryable(text: str) -> bool:
    """Backend-init flakiness worth retrying vs. a real bug worth surfacing."""
    needles = (
        "UNAVAILABLE",
        "Unable to initialize backend",
        "TPU backend setup/compile error",
        "DEADLINE_EXCEEDED",
        "failed to connect",
    )
    return any(n in text for n in needles)


def _relay_port_down() -> bool:
    """Cheap liveness probe for the local TPU relay (axon environments only).

    When the tunnel plugin is registered (``PALLAS_AXON_POOL_IPS`` set) and
    its local relay port is closed, EVERY child python hangs at interpreter
    start retrying the tunnel — so spawning one just burns the budget. On
    non-axon hosts (driver running against real hardware directly) there is
    no relay and this never gates anything.
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return False
    import socket

    s = socket.socket()
    s.settimeout(1.0)
    try:
        s.connect(("127.0.0.1", 8082))
        return False
    except OSError:
        return True
    finally:
        s.close()


def main() -> None:
    if os.environ.get(_INNER_ENV) == "1":
        _run_inner()
        return

    deadline = time.monotonic() + _TOTAL_BUDGET_S
    last_err = ""
    attempt = 0
    relay_probes = 0
    # Only infrastructure failures (relay down, tunnel hang, UNAVAILABLE)
    # may fall back to a stale banked row. A deterministic error means the
    # benchmark itself is broken — serving an old number with rc=0 would
    # mask a real regression, so that path stays value:null + rc=1.
    infra_failure = True
    while True:
        remaining = deadline - time.monotonic()
        if remaining < 30:  # not enough left for a useful attempt
            if not last_err:
                last_err = "no benchmark attempt fit inside the time budget"
            break
        attempt += 1
        if _relay_port_down():
            # A closed relay port almost never heals inside the bench
            # window (r4: 19 probe/sleep cycles burned the entire budget
            # before the banked row went out). Probe at most
            # _RELAY_MAX_PROBES times with short sleeps, then emit the
            # fallback row immediately with ~all the budget unspent.
            relay_probes += 1
            last_err = (
                "TPU relay port (127.0.0.1:8082) is down; backend unreachable"
            )
            print(
                f"bench attempt {attempt}: relay port down "
                f"(probe {relay_probes}/{_RELAY_MAX_PROBES}), "
                f"{remaining:.0f}s of budget left",
                file=sys.stderr,
            )
            _emit_event(
                "bench.relay_probe", attempt=attempt, probe=relay_probes,
                max_probes=_RELAY_MAX_PROBES, remaining_s=round(remaining, 1),
            )
            if relay_probes >= _RELAY_MAX_PROBES:
                break  # straight to the banked-row fallback
            time.sleep(min(2.0, remaining))
            continue
        # The cap means CONSECUTIVE down-probes: a port that answered again
        # earns a fresh budget, so separated blips can't drain it mid-run.
        relay_probes = 0
        try:
            # Child timeout is whatever budget remains (minus a margin to
            # print the failure line): a hung tunnel can never push the
            # wrapper past its total budget.
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env={**os.environ, _INNER_ENV: "1"},
                capture_output=True,
                text=True,
                timeout=max(remaining - 10.0, 20.0),
            )
        except subprocess.TimeoutExpired:
            last_err = "benchmark subprocess timed out (TPU tunnel hung?)"
            _emit_event("bench.attempt", attempt=attempt, outcome="timeout")
            continue  # budget check at the top of the loop bounds this
        sys.stderr.write(proc.stderr)
        if proc.returncode == 0 and '"value"' in proc.stdout:
            sys.stdout.write(proc.stdout)
            _bank_success(proc.stdout)
            _emit_event("bench.attempt", attempt=attempt, outcome="ok")
            return
        last_err = (proc.stderr or "") + (proc.stdout or "")
        if not _looks_retryable(last_err):
            infra_failure = False
            _emit_event(
                "bench.attempt", attempt=attempt, outcome="deterministic_failure",
                rc=proc.returncode,
            )
            break  # deterministic failure: retrying would just burn time
        _emit_event(
            "bench.attempt", attempt=attempt, outcome="retryable_failure",
            rc=proc.returncode,
        )
        time.sleep(min(5.0, max(deadline - time.monotonic(), 0.0)))

    # Final failure. Prefer the newest banked base row (clearly marked stale)
    # over value:null: a dead relay during the bench window must not erase a
    # number measured earlier in the round (round 3 lost its signal this way).
    tail = "\n".join(last_err.strip().splitlines()[-5:])
    banked = _latest_banked_base() if infra_failure else None
    if banked is not None:
        row, path = banked
        out = {
            "metric": _METRIC,
            "value": row["value"],
            "unit": row.get("unit", "tokens/sec/chip"),
            "vs_baseline": None,
            "stale": True,
            "stale_reason": tail or "benchmark subprocess produced no output",
            "stale_source": f"{os.path.basename(path)} (newest banked base row)",
        }
        # Surface how stale: the consumer decides whether a rounds-old row
        # is still meaningful (no hard age cutoff — the VERDICT-requested
        # behavior is "latest banked row, clearly labeled", and a labeled
        # old number beats value:null for trend tracking).
        if row.get("device"):
            out["stale_device"] = row["device"]
        if row.get("ts"):
            out["stale_age_s"] = round(time.time() - float(row["ts"]), 1)
        elif row.get("source"):
            out["stale_provenance"] = row["source"]
        _emit_event(
            "bench.fallback_row", value=row["value"],
            stale_source=out["stale_source"],
            stale_age_s=out.get("stale_age_s"),
            stale_reason=tail.splitlines()[-1] if tail else "",
        )
        print(json.dumps(out))
        return  # rc=0: the line carries a real (if stale) measurement
    _emit_event(
        "bench.no_value",
        infra_failure=infra_failure,
        error=tail.splitlines()[-1] if tail else "no output",
    )
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": None,
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
                "error": tail or "benchmark subprocess produced no output",
            }
        )
    )
    sys.exit(1)


if __name__ == "__main__":
    main()
