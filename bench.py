"""Benchmark: Transformer-base training throughput, tokens/sec/chip.

Runs the flagship train step (BASELINE.json configs[1]: 6L, d_model=512,
8 heads, dff=2048, bf16 compute) on whatever accelerator jax exposes (the
driver runs this on one real TPU chip), times steady-state steps, and prints
ONE JSON line:

    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": X}

``vs_baseline`` is null: the reference publishes no numbers (BASELINE.md —
README is a bare feature list), so there is nothing to normalize against.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax
    import numpy as np

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.train import create_train_state, make_train_step

    batch, seq = 64, 64
    model_cfg = ModelConfig(
        num_layers=6,
        d_model=512,
        num_heads=8,
        dff=2048,
        input_vocab_size=32002,
        target_vocab_size=32002,
        max_position=seq,
        dropout_rate=0.1,
        dtype="bfloat16",
    )
    train_cfg = TrainConfig(
        batch_size=batch, sequence_length=seq, warmup_steps=4000,
    )

    dev = jax.devices()[0]
    print(f"benchmarking on {dev.platform}:{dev.device_kind}", file=sys.stderr)

    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=(0,))
    rng = jax.random.PRNGKey(1)
    r = np.random.default_rng(0)
    src = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))
    tgt = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))

    # Warmup: compile + 2 steady steps. Synchronize via a VALUE fetch, not
    # block_until_ready: on tunneled/remote PJRT backends block_until_ready
    # can return before device execution finishes, inflating throughput.
    for _ in range(3):
        state, metrics = step(state, src, tgt, rng)
    float(metrics["loss"])

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, src, tgt, rng)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss"

    # Tokens processed per optimizer step: target tokens (the unit BLEU-side
    # throughput is quoted in). src+tgt would double-count the same sentence.
    tokens_per_step = batch * (seq - 1)
    value = tokens_per_step * n_steps / dt

    # Rough MFU estimate for context (stderr only): 6*P FLOPs/token fwd+bwd*3.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    flops_per_token = 6 * n_params
    print(
        f"{n_steps} steps in {dt:.2f}s, {value:,.0f} tok/s, "
        f"~{value * flops_per_token / 1e12:.2f} TFLOP/s model-flops "
        f"({n_params / 1e6:.1f}M params)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "transformer-base train throughput (6L/512/8H/2048, bf16, batch 64, seq 64)",
                "value": round(value, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
            }
        )
    )


if __name__ == "__main__":
    main()
