"""Benchmark: Transformer-base training throughput, tokens/sec/chip.

Runs the flagship train step (BASELINE.json configs[1]: 6L, d_model=512,
8 heads, dff=2048, bf16 compute) on whatever accelerator jax exposes (the
driver runs this on one real TPU chip), times steady-state steps, and prints
ONE JSON line:

    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": X}

``vs_baseline`` is null: the reference publishes no numbers (BASELINE.md —
README is a bare feature list), so there is nothing to normalize against.

Resilience: the TPU tunnel can be transiently down (round 1 captured exactly
that: ``jax.errors.JaxRuntimeError: UNAVAILABLE`` at backend init). A failed
backend init is cached for the life of the process, so the measurement runs
in a child process; the parent retries with bounded backoff and, if every
attempt fails, emits a structured failure JSON line instead of a traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_INNER_ENV = "_TRANSFORMER_TPU_BENCH_INNER"
_METRIC = "transformer-base train throughput (6L/512/8H/2048, bf16, batch 64, seq 64)"
# 0 + 15 + 30 + 60 + 120 ≈ 4 minutes of patience for a flapping tunnel.
_RETRY_DELAYS_S = (0, 15, 30, 60, 120)


def _run_inner() -> None:
    """The actual measurement. Runs in a child process (fresh backend)."""
    import jax
    import numpy as np

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.train import create_train_state, make_train_step

    batch, seq = 64, 64
    model_cfg = ModelConfig(
        num_layers=6,
        d_model=512,
        num_heads=8,
        dff=2048,
        input_vocab_size=32002,
        target_vocab_size=32002,
        max_position=seq,
        dropout_rate=0.1,
        dtype="bfloat16",
    )
    train_cfg = TrainConfig(
        batch_size=batch, sequence_length=seq, warmup_steps=4000,
    )

    dev = jax.devices()[0]
    print(f"benchmarking on {dev.platform}:{dev.device_kind}", file=sys.stderr)

    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=(0,))
    rng = jax.random.PRNGKey(1)
    r = np.random.default_rng(0)
    src = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))
    tgt = jax.device_put(r.integers(1, 32000, (batch, seq), dtype=np.int32))

    # Warmup: compile + 2 steady steps. Synchronize via a VALUE fetch, not
    # block_until_ready: on tunneled/remote PJRT backends block_until_ready
    # can return before device execution finishes, inflating throughput.
    for _ in range(3):
        state, metrics = step(state, src, tgt, rng)
    float(metrics["loss"])

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, src, tgt, rng)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss"

    # Tokens processed per optimizer step: target tokens (the unit BLEU-side
    # throughput is quoted in). src+tgt would double-count the same sentence.
    tokens_per_step = batch * (seq - 1)
    value = tokens_per_step * n_steps / dt

    # Rough MFU estimate for context (stderr only): 6*P FLOPs/token fwd+bwd*3.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    flops_per_token = 6 * n_params
    print(
        f"{n_steps} steps in {dt:.2f}s, {value:,.0f} tok/s, "
        f"~{value * flops_per_token / 1e12:.2f} TFLOP/s model-flops "
        f"({n_params / 1e6:.1f}M params)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": round(value, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
            }
        )
    )


def _looks_retryable(text: str) -> bool:
    """Backend-init flakiness worth retrying vs. a real bug worth surfacing."""
    needles = (
        "UNAVAILABLE",
        "Unable to initialize backend",
        "TPU backend setup/compile error",
        "DEADLINE_EXCEEDED",
        "failed to connect",
    )
    return any(n in text for n in needles)


def main() -> None:
    if os.environ.get(_INNER_ENV) == "1":
        _run_inner()
        return

    last_err = ""
    for attempt, delay in enumerate(_RETRY_DELAYS_S, start=1):
        if delay:
            print(
                f"bench attempt {attempt - 1} failed (backend unavailable); "
                f"retrying in {delay}s",
                file=sys.stderr,
            )
            time.sleep(delay)
        try:
            # Bounded: with the tunnel relay dead, the child hangs at
            # interpreter start (sitecustomize retries the tunnel forever),
            # and without a timeout this wrapper would never emit its
            # structured failure line.
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env={**os.environ, _INNER_ENV: "1"},
                capture_output=True,
                text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired:
            last_err = "benchmark subprocess timed out (TPU tunnel hung?)"
            continue  # retryable: the tunnel may come back
        sys.stderr.write(proc.stderr)
        if proc.returncode == 0 and '"value"' in proc.stdout:
            sys.stdout.write(proc.stdout)
            return
        last_err = (proc.stderr or "") + (proc.stdout or "")
        if not _looks_retryable(last_err):
            break  # deterministic failure: retrying would just burn time

    # Final failure: one structured JSON line, not a traceback.
    tail = "\n".join(last_err.strip().splitlines()[-5:])
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": None,
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
                "error": tail or "benchmark subprocess produced no output",
            }
        )
    )
    sys.exit(1)


if __name__ == "__main__":
    main()
